"""Speech, anomaly detection, translation, form recognizer, Bing search.

Reference: cognitive/SpeechToText.scala (131 LoC), AnomalyDetection.scala
(249 LoC), TextTranslator.scala (406 LoC), FormRecognizer.scala (353 LoC),
BingImageSearch.scala (309 LoC).
"""
from __future__ import annotations

import json
from typing import Dict, List
from urllib.parse import urlencode

from ..core.params import Param, ServiceParam, TypeConverters
from ..core.registry import register_stage
from ..core.schema import Table
from ..io.http.schema import HTTPRequestData
from .base import BasicAsyncReply, CognitiveServicesBase
from .vision import HasImageInput

__all__ = [
    "SpeechToText",
    "DetectLastAnomaly",
    "DetectAnomalies",
    "SimpleDetectAnomalies",
    "Translate",
    "Detect",
    "BreakSentence",
    "Transliterate",
    "DictionaryLookup",
    "DictionaryExamples",
    "AnalyzeLayout",
    "AnalyzeInvoices",
    "AnalyzeReceipts",
    "AnalyzeBusinessCards",
    "AnalyzeIDDocuments",
    "AnalyzeCustomModel",
    "GetCustomModel",
    "ListCustomModels",
    "DocumentTranslator",
    "BingImageSearch",
]


@register_stage
class SpeechToText(CognitiveServicesBase):
    """REST speech recognition (SpeechToText.scala — the SDK streaming
    variant is host-side audio plumbing with the same output schema)."""

    _domain = "stt.speech.microsoft.com"
    _path = "/speech/recognition/conversation/cognitiveservices/v1"
    audio_col = Param("column of audio bytes (wav)", default="audio")
    language = ServiceParam("recognition language", default="en-US")
    format = Param("simple|detailed", default="simple")

    def _prepare_url(self, table, i):
        q = urlencode({"language": self.resolve("language", table, i),
                       "format": self.format})
        return f"{self._base_url()}?{q}"

    def _headers(self, table, i):
        h = super()._headers(table, i)
        h["Content-Type"] = "audio/wav; codecs=audio/pcm; samplerate=16000"
        return h

    def _prepare_entity(self, table, i):
        a = table[self.audio_col][i]
        return bytes(a) if a is not None else None


class _AnomalyBase(CognitiveServicesBase):
    """Series payload from columns of timestamps+values
    (AnomalyDetection.scala)."""

    timestamps_col = Param("column of per-row timestamp lists", default="timestamps")
    values_col = Param("column of per-row value lists", default="values")
    granularity = ServiceParam("series granularity", default="daily")
    sensitivity = ServiceParam("sensitivity 0-99", default=None)

    def _prepare_entity(self, table, i):
        ts = table[self.timestamps_col][i]
        vals = table[self.values_col][i]
        if ts is None or vals is None:
            return None
        series = [{"timestamp": str(t), "value": float(v)}
                  for t, v in zip(ts, vals)]
        body = {"series": series,
                "granularity": self.resolve("granularity", table, i)}
        sens = self.resolve("sensitivity", table, i)
        if sens is not None:
            body["sensitivity"] = int(sens)
        return json.dumps(body).encode()


@register_stage
class DetectLastAnomaly(_AnomalyBase):
    _path = "/anomalydetector/v1.0/timeseries/last/detect"


@register_stage
class DetectAnomalies(_AnomalyBase):
    _path = "/anomalydetector/v1.0/timeseries/entire/detect"


@register_stage
class SimpleDetectAnomalies(CognitiveServicesBase):
    """Row-wise anomaly detection with grouping (AnomalyDetection.scala:249
    SimpleDetectAnomalies): rows carry (timestamp, value, group); each group
    becomes ONE entire-series request sorted by timestamp, and the per-point
    verdict joins back onto its row."""

    _path = "/anomalydetector/v1.0/timeseries/entire/detect"
    timestamp_col = Param("per-row timestamp column", default="timestamp")
    value_col = Param("per-row value column", default="value")
    group_col = Param("series grouping column", default="group")
    granularity = ServiceParam("series granularity", default="daily")
    sensitivity = ServiceParam("sensitivity 0-99", default=None)

    def _prepare_entity(self, table, i):  # driven by the grouped _transform
        raise NotImplementedError

    @staticmethod
    def _ts_key(v):
        """Chronological sort key: numerics numerically, ISO-8601 via
        datetime parsing (lexicographic order misorders epoch ints and
        non-zero-padded dates; the service 400s on unsorted series)."""
        import datetime as _dt

        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return (0, float(v), "")
        s = str(v)
        try:
            return (0, float(s), "")
        except ValueError:
            pass
        try:
            return (0, _dt.datetime.fromisoformat(
                s.replace("Z", "+00:00")).timestamp(), "")
        except ValueError:
            return (1, 0.0, s)

    def _transform(self, table: Table) -> Table:
        import math

        import numpy as np

        n = len(table)
        groups = table[self.group_col]
        ts = table[self.timestamp_col]
        vals = table[self.value_col]
        skipped = np.zeros(n, bool)
        order_of: Dict[object, List[int]] = {}
        for i in range(n):
            v = vals[i]
            # base-class contract: a null row is skipped (null output), not
            # a crash — and it must not poison its whole group's series
            if ts[i] is None or v is None or (
                    isinstance(v, float) and math.isnan(v)):
                skipped[i] = True
                continue
            order_of.setdefault(groups[i], []).append(i)

        reqs, row_maps = [], []
        for g, rows in order_of.items():
            rows = sorted(rows, key=lambda r: self._ts_key(ts[r]))
            series = [{"timestamp": str(ts[r]), "value": float(vals[r])}
                      for r in rows]
            body = {"series": series,
                    "granularity": self.resolve("granularity", table, rows[0])}
            sens = self.resolve("sensitivity", table, rows[0])
            if sens is not None:
                body["sensitivity"] = int(sens)
            reqs.append(HTTPRequestData(
                url=self._prepare_url(table, rows[0]), method="POST",
                headers=self._headers(table, rows[0]),
                entity=json.dumps(body).encode()))
            row_maps.append(rows)

        resps = self._client().send_all(reqs)
        out = np.empty(n, dtype=object)
        errs = np.empty(n, dtype=object)
        errs[:] = None
        for rows, resp in zip(row_maps, resps):
            if resp is None or not resp.ok:
                msg = None if resp is None else f"{resp.status_code} {resp.reason}"
                for r in rows:
                    errs[r] = msg
                continue
            payload = self._postprocess(resp) or {}
            for k, r in enumerate(rows):
                out[r] = {key: (v[k] if isinstance(v, list) and k < len(v)
                                else v)
                          for key, v in payload.items()}
        result = table.with_column(self.output_col, out)
        if self.error_col:
            result = result.with_column(self.error_col, errs)
        return result


class _TranslatorBase(CognitiveServicesBase):
    _domain = "cognitive.microsofttranslator.com"
    text_col = Param("input text column", default="text")

    def _base_url(self) -> str:
        if self.url:
            return self.url
        return f"https://api.{self._domain}{self._path}"

    def _prepare_entity(self, table, i):
        t = table[self.text_col][i]
        return None if t is None else json.dumps([{"Text": str(t)}]).encode()


@register_stage
class Translate(_TranslatorBase):
    _path = "/translate"
    to_language = ServiceParam("target language(s), comma-joined", default="en")

    def _prepare_url(self, table, i):
        to = str(self.resolve("to_language", table, i))
        q = [("api-version", "3.0")] + [("to", x) for x in to.split(",")]
        return f"{self._base_url()}?{urlencode(q)}"


@register_stage
class Detect(_TranslatorBase):
    _path = "/detect"

    def _prepare_url(self, table, i):
        return f"{self._base_url()}?api-version=3.0"


@register_stage
class BreakSentence(_TranslatorBase):
    _path = "/breaksentence"

    def _prepare_url(self, table, i):
        return f"{self._base_url()}?api-version=3.0"


@register_stage
class DictionaryLookup(_TranslatorBase):
    """Alternative translations for a word/phrase (TextTranslator.scala
    DictionaryLookup)."""

    _path = "/dictionary/lookup"
    from_language = ServiceParam("source language", default="en")
    to_language = ServiceParam("target language", default="es")

    def _prepare_url(self, table, i):
        q = urlencode({"api-version": "3.0",
                       "from": self.resolve("from_language", table, i),
                       "to": self.resolve("to_language", table, i)})
        return f"{self._base_url()}?{q}"


@register_stage
class DictionaryExamples(_TranslatorBase):
    """Usage examples for a (text, translation) pair (TextTranslator.scala
    DictionaryExamples); the input column holds (text, translation) pairs."""

    _path = "/dictionary/examples"
    text_and_translation_col = Param(
        "column of (text, translation) pairs", default="textAndTranslation")
    from_language = ServiceParam("source language", default="en")
    to_language = ServiceParam("target language", default="es")

    def _prepare_url(self, table, i):
        q = urlencode({"api-version": "3.0",
                       "from": self.resolve("from_language", table, i),
                       "to": self.resolve("to_language", table, i)})
        return f"{self._base_url()}?{q}"

    def _prepare_entity(self, table, i):
        v = table[self.text_and_translation_col][i]
        if v is None:
            return None
        text, translation = v
        return json.dumps(
            [{"Text": str(text), "Translation": str(translation)}]).encode()


@register_stage
class Transliterate(_TranslatorBase):
    _path = "/transliterate"
    language = ServiceParam("source language", default="ja")
    from_script = ServiceParam("source script", default="Jpan")
    to_script = ServiceParam("target script", default="Latn")

    def _prepare_url(self, table, i):
        q = urlencode({
            "api-version": "3.0",
            "language": self.resolve("language", table, i),
            "fromScript": self.resolve("from_script", table, i),
            "toScript": self.resolve("to_script", table, i),
        })
        return f"{self._base_url()}?{q}"


class _FormRecognizerBase(HasImageInput, BasicAsyncReply):
    """Async layout/invoice analysis (FormRecognizer.scala); URL-mode bodies
    use the form-recognizer 'source' field."""

    _url_key = "source"


class _HasModelsBase:
    """Shared custom-models endpoint construction (normalized trailing /)."""

    def _models_base(self) -> str:
        base = self.url or (f"https://{self.location}.{self._domain}"
                            "/formrecognizer/v2.1/custom/models")
        return base.rstrip("/")


@register_stage
class AnalyzeLayout(_FormRecognizerBase):
    _path = "/formrecognizer/v2.1/layout/analyze"


@register_stage
class AnalyzeInvoices(_FormRecognizerBase):
    _path = "/formrecognizer/v2.1/prebuilt/invoice/analyze"


@register_stage
class AnalyzeReceipts(_FormRecognizerBase):
    """FormRecognizer.scala AnalyzeReceipts."""

    _path = "/formrecognizer/v2.1/prebuilt/receipt/analyze"


@register_stage
class AnalyzeBusinessCards(_FormRecognizerBase):
    """FormRecognizer.scala AnalyzeBusinessCards."""

    _path = "/formrecognizer/v2.1/prebuilt/businessCard/analyze"


@register_stage
class AnalyzeIDDocuments(_FormRecognizerBase):
    """FormRecognizer.scala AnalyzeIDDocuments."""

    _path = "/formrecognizer/v2.1/prebuilt/idDocument/analyze"


@register_stage
class AnalyzeCustomModel(_HasModelsBase, _FormRecognizerBase):
    """Analysis against a trained custom model (FormRecognizer.scala
    AnalyzeCustomModel): the model id routes the request."""

    model_id = ServiceParam("trained custom model id", default=None)

    def _prepare_url(self, table, i):
        mid = self.resolve("model_id", table, i)
        if not mid:
            raise ValueError("AnalyzeCustomModel requires model_id")
        return f"{self._models_base()}/{mid}/analyze"


@register_stage
class GetCustomModel(_HasModelsBase, CognitiveServicesBase):
    """Fetch one custom model's metadata (FormRecognizer.scala
    GetCustomModel): a GET per row, keyed by the model-id value-or-column."""

    model_id = ServiceParam("custom model id", default=None)
    include_keys = Param("include extracted keys", default=False,
                         converter=TypeConverters.to_bool)

    def _prepare_method(self):
        return "GET"

    def _prepare_entity(self, table, i):
        return b""  # GET: non-None marks the row active

    def _prepare_url(self, table, i):
        mid = self.resolve("model_id", table, i)
        if not mid:
            raise ValueError("GetCustomModel requires model_id")
        url = f"{self._models_base()}/{mid}"
        return url + ("?includeKeys=true" if self.include_keys else "")


@register_stage
class ListCustomModels(_HasModelsBase, CognitiveServicesBase):
    """List the resource's custom models (FormRecognizer.scala
    ListCustomModels); `op` selects full vs summary listings."""

    op = Param("full|summary", default="full")

    def _prepare_method(self):
        return "GET"

    def _prepare_entity(self, table, i):
        return b""

    def _prepare_url(self, table, i):
        return f"{self._models_base()}?{urlencode({'op': self.op})}"


@register_stage
class DocumentTranslator(BasicAsyncReply):
    """Batch document translation: POST a batches spec, poll the operation
    (reference cognitive/DocumentTranslator.scala, 151 LoC)."""

    _path = "/translator/text/batch/v1.0/batches"
    service_name = Param("translator resource name", default="")
    inputs_col = Param("column of batch-input dicts "
                       "(sourceUrl/targets per the service spec)",
                       default="batches")

    def _base_url(self) -> str:
        if self.url:
            return self.url
        return (f"https://{self.service_name}.cognitiveservices.azure.com"
                f"{self._path}")

    def _prepare_entity(self, table, i):
        v = table[self.inputs_col][i]
        return None if v is None else json.dumps({"inputs": v}).encode()


@register_stage
class BingImageSearch(CognitiveServicesBase):
    """Bing image search (BingImageSearch.scala): GET with query params."""

    _domain = "api.bing.microsoft.com"
    _path = "/v7.0/images/search"
    query_col = Param("search query column", default="query")
    count = Param("results per query", default=10,
                  converter=TypeConverters.to_int)
    offset_col = Param("optional per-row offset column", default="")

    def _base_url(self) -> str:
        return self.url or f"https://{self._domain}{self._path}"

    def _prepare_method(self):
        return "GET"

    def _prepare_entity(self, table, i):
        q = table[self.query_col][i]
        return b"" if q is not None else None

    def _prepare_url(self, table, i):
        params = {"q": str(table[self.query_col][i]),
                  "count": int(self.count)}
        if self.offset_col:
            params["offset"] = int(table[self.offset_col][i])
        return f"{self._base_url()}?{urlencode(params)}"

    @staticmethod
    def get_urls(table: Table, output_col: str = "output",
                 url_col: str = "imageUrl") -> Table:
        """Flatten contentUrls out of search responses
        (BingImageSearch.getUrlTransformer)."""
        import numpy as np

        urls = []
        for r in table[output_col]:
            for v in (r or {}).get("value", []):
                if "contentUrl" in v:
                    urls.append(v["contentUrl"])
        arr = np.empty(len(urls), dtype=object)
        for i, u in enumerate(urls):
            arr[i] = u
        return Table({url_col: arr})
