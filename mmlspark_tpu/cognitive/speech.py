"""Streaming speech recognition: chunked audio -> incremental results.

Reference: cognitive/SpeechToTextSDK.scala:76-489 — per-row recognizers fed
by pulled audio streams (WavStream / CompressedStream, AudioStreams.scala:94)
emitting a row per recognized utterance.  The native Speech SDK's websocket
session is replaced by windowed recognition requests over the same REST
surface as `SpeechToText`: each audio window is posted as one utterance and
the per-row output is the ordered list of segment results (optionally
flattened to a row per utterance, matching the reference's emitted rows).
"""
from __future__ import annotations

import json
import struct
from typing import Iterator, List, Optional, Tuple
from urllib.parse import urlencode

import numpy as np

from ..core.params import Param, ServiceParam, TypeConverters
from ..core.registry import register_stage
from ..core.schema import Table
from ..io.http.schema import HTTPRequestData
from .base import CognitiveServicesBase

__all__ = ["WavStream", "CompressedStream", "SpeechToTextSDK",
           "ConversationTranscription"]


class WavStream:
    """Pulled WAV audio stream (AudioStreams.scala WavStream): parses the
    RIFF header and yields windows of whole PCM frames."""

    def __init__(self, data: bytes):
        if len(data) < 44 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
            raise ValueError("not a RIFF/WAVE stream")
        # walk chunks to find fmt + data (canonical files: fmt at 12, data later)
        pos = 12
        self.sample_rate = 16000
        self.channels = 1
        self.bits_per_sample = 16
        self.pcm = b""
        while pos + 8 <= len(data):
            cid = data[pos:pos + 4]
            size = struct.unpack("<I", data[pos + 4:pos + 8])[0]
            body = data[pos + 8:pos + 8 + size]
            if cid == b"fmt ":
                (_fmt, self.channels, self.sample_rate, _bps, _align,
                 self.bits_per_sample) = struct.unpack("<HHIIHH", body[:16])
            elif cid == b"data":
                self.pcm = body
            pos += 8 + size + (size % 2)
        self.frame_bytes = max(self.channels * self.bits_per_sample // 8, 1)

    @property
    def duration_ms(self) -> float:
        frames = len(self.pcm) // self.frame_bytes
        return 1000.0 * frames / max(self.sample_rate, 1)

    def windows(self, window_ms: int) -> Iterator[Tuple[float, bytes]]:
        """(offset_ms, pcm_window) pairs of whole frames."""
        rate = max(self.sample_rate, 1)  # corrupt fmt chunks declare 0
        frames_per_window = max(int(rate * window_ms / 1000.0), 1)
        step = frames_per_window * self.frame_bytes
        for off in range(0, len(self.pcm), step):
            offset_ms = 1000.0 * (off // self.frame_bytes) / rate
            yield offset_ms, self.pcm[off:off + step]

    def utterances(self, silence_ms: int = 300, frame_ms: int = 30,
                   energy_threshold: Optional[float] = None,
                   min_utterance_ms: int = 100,
                   max_utterance_ms: int = 20000
                   ) -> Iterator[Tuple[float, bytes]]:
        """(offset_ms, pcm_segment) per detected utterance — energy/silence
        endpointing over PCM frames, the native SDK's event-driven
        continuous-recognition semantics (SpeechToTextSDK.scala:76-489):
        segments end at pauses, never mid-word.

        A frame is voiced when its RMS exceeds the threshold (auto: the
        louder of ~1% full scale and 2x the 20th-percentile frame RMS, so
        both digital silence and mild noise floors endpoint cleanly).  A
        run of `silence_ms` unvoiced frames closes the utterance; segments
        get one frame of leading/trailing context, blips shorter than
        `min_utterance_ms` are dropped, and `max_utterance_ms` force-splits
        so one long monologue can't become an unbounded request.  Non-16-bit
        PCM falls back to fixed `max_utterance_ms` windows (no decode path).
        """
        if self.bits_per_sample != 16 or not self.pcm:
            yield from self.windows(max_utterance_ms)
            return
        x = np.frombuffer(
            self.pcm[:len(self.pcm) - len(self.pcm) % self.frame_bytes],
            dtype="<i2").astype(np.float32)
        if self.channels > 1:
            x = x.reshape(-1, self.channels).mean(axis=1)
        rate = max(self.sample_rate, 1)  # corrupt fmt chunks declare 0
        spf = max(int(rate * frame_ms / 1000.0), 1)
        n_frames = -(-len(x) // spf)
        pad = np.zeros(n_frames * spf - len(x), np.float32)
        frames = np.concatenate([x, pad]).reshape(n_frames, spf)
        rms = np.sqrt(np.mean(frames * frames, axis=1))
        if energy_threshold is None:
            # 2x the quiet end of the tape (but at least ~1% full scale),
            # capped at half its loud end so quiet-but-real speech and
            # tapes with no silence both stay voiced; the ~0.2%-scale
            # outer floor keeps a noise-only tape from becoming speech
            floor = float(np.percentile(rms, 20)) if n_frames else 0.0
            loud = float(np.percentile(rms, 95)) if n_frames else 0.0
            energy_threshold = max(
                65.0, min(max(327.0, 2.0 * floor), 0.5 * loud))
        voiced = rms > energy_threshold
        silence_frames = max(int(round(silence_ms / frame_ms)), 1)
        min_frames = max(int(round(min_utterance_ms / frame_ms)), 1)
        max_frames = max(int(round(max_utterance_ms / frame_ms)), 1)

        def emit(f0: int, f1: int):
            # one frame of context each side; slice whole PCM frames
            f0, f1 = max(f0 - 1, 0), min(f1 + 1, n_frames)
            s0, s1 = f0 * spf, min(f1 * spf, len(x))
            off_ms = 1000.0 * s0 / rate
            return off_ms, self.pcm[s0 * self.frame_bytes:
                                    s1 * self.frame_bytes]

        start = None   # first voiced frame of the open utterance
        last_voiced = None
        for f in range(n_frames):
            if voiced[f]:
                if start is None:
                    start = f
                last_voiced = f
                if f - start + 1 >= max_frames:  # force-split
                    yield emit(start, f + 1)
                    start = last_voiced = None
            elif start is not None and f - last_voiced >= silence_frames:
                if last_voiced - start + 1 >= min_frames:
                    yield emit(start, last_voiced + 1)
                start = last_voiced = None
        if start is not None and last_voiced - start + 1 >= min_frames:
            yield emit(start, last_voiced + 1)

    def window_wav(self, pcm_window: bytes) -> bytes:
        """Re-wrap a PCM window in a minimal WAV container so each request
        is a self-describing utterance."""
        byte_rate = self.sample_rate * self.frame_bytes
        hdr = struct.pack(
            "<4sI4s4sIHHIIHH4sI",
            b"RIFF", 36 + len(pcm_window), b"WAVE", b"fmt ", 16, 1,
            self.channels, self.sample_rate, byte_rate, self.frame_bytes,
            self.bits_per_sample, b"data", len(pcm_window))
        return hdr + pcm_window


class CompressedStream:
    """Opaque compressed audio (AudioStreams.scala CompressedStream): no
    header knowledge — fixed-size byte windows, offsets unknown."""

    def __init__(self, data: bytes):
        self.data = data

    def windows(self, window_bytes: int) -> Iterator[Tuple[float, bytes]]:
        for off in range(0, len(self.data), window_bytes):
            yield -1.0, self.data[off:off + window_bytes]


@register_stage
class SpeechToTextSDK(CognitiveServicesBase):
    """Continuous recognition over per-row audio streams.

    Reference: SpeechToTextSDK.scala:76-489.  Wav rows are segmented at
    silence boundaries (energy endpointing over PCM frames — the native
    SDK recognizer's event-driven utterance semantics; words are never
    split at arbitrary window edges); compressed rows fall back to fixed
    byte windows.  Every segment is recognized as one utterance;
    `output_col` holds the ordered list of result dicts, each annotated
    with its stream offset.  With `flatten_results` the stage emits one
    row per utterance instead — the reference's emitted-row shape.
    """

    _domain = "stt.speech.microsoft.com"
    _path = "/speech/recognition/conversation/cognitiveservices/v1"
    audio_col = Param("column of audio bytes", default="audio")
    language = ServiceParam("recognition language", default="en-US")
    format = Param("simple|detailed", default="simple")
    stream_format = Param("wav|compressed (windowing strategy)", default="wav")
    segmentation = Param(
        "utterance|window — wav streams segment at silence boundaries "
        "(energy endpointing; the native SDK's continuous-recognition "
        "semantics) or into fixed window_ms windows", default="utterance")
    silence_ms = Param("pause length that ends an utterance", default=300,
                       converter=TypeConverters.to_int)
    energy_threshold = Param("RMS frame-energy voicing threshold "
                             "(None = adaptive)", default=None)
    min_utterance_ms = Param("drop voiced blips shorter than this",
                             default=100, converter=TypeConverters.to_int)
    max_utterance_ms = Param("force-split utterances longer than this",
                             default=20000, converter=TypeConverters.to_int)
    window_ms = Param("recognition window for wav streams (ms) when "
                      "segmentation='window'", default=2000,
                      converter=TypeConverters.to_int)
    window_bytes = Param("recognition window for compressed streams (bytes)",
                         default=32768, converter=TypeConverters.to_int)
    flatten_results = Param("emit a row per utterance instead of a list "
                            "per input row", default=False,
                            converter=TypeConverters.to_bool)

    def _recognize_url(self, table, i) -> str:
        base = self._base_url()
        sep = "&" if "?" in base else "?"  # user urls may carry a query
        q = urlencode({"language": self.resolve("language", table, i),
                       "format": self.format})
        return f"{base}{sep}{q}"

    def _check_segmentation(self) -> str:
        seg_mode = self.get_or_default("segmentation")
        if seg_mode not in ("utterance", "window"):
            raise ValueError(
                f"segmentation must be 'utterance' or 'window', got "
                f"{seg_mode!r}")
        return seg_mode

    def _windows(self, audio: bytes):
        if self.stream_format == "wav":
            stream = WavStream(bytes(audio))
            if self.get_or_default("segmentation") == "utterance":
                thr = self.get_or_default("energy_threshold")
                segs = stream.utterances(
                    silence_ms=int(self.silence_ms),
                    energy_threshold=None if thr is None else float(thr),
                    min_utterance_ms=int(self.min_utterance_ms),
                    max_utterance_ms=int(self.max_utterance_ms))
            else:
                segs = stream.windows(int(self.window_ms))
            return [(off, stream.window_wav(w)) for off, w in segs]
        stream = CompressedStream(bytes(audio))
        return list(stream.windows(int(self.window_bytes)))

    def _transform(self, table: Table) -> Table:
        # validate config BEFORE the per-row loop: a typo'd segmentation
        # value must fail the stage, not be swallowed as a row error
        self._check_segmentation()
        n = len(table)
        audio_col = table[self.audio_col]
        # every window of every row is one request through the shared
        # bounded-concurrency pool (the continuous-recognition firehose)
        reqs: List[Optional[HTTPRequestData]] = []
        spans: List[Tuple[int, float]] = []  # (row, offset_ms) per request
        decode_errs: dict = {}
        for i in range(n):
            audio = audio_col[i]
            if audio is None:
                continue
            try:
                windows = self._windows(audio)
            except (ValueError, struct.error) as e:
                # one corrupt row must not fail the whole stage: route it
                # to error_col (SpeechToTextSDK.scala's per-row recognizer
                # failure isolation)
                decode_errs[i] = f"audio decode failed: {e}"
                continue
            for off, blob in windows:
                hdr = self._headers(table, i)
                hdr["Content-Type"] = ("audio/wav; codecs=audio/pcm; "
                                       "samplerate=16000")
                reqs.append(HTTPRequestData(
                    url=self._recognize_url(table, i), method="POST",
                    headers=hdr, entity=blob))
                spans.append((i, off))
        resps = self._client().send_all(reqs)

        per_row: List[List[dict]] = [[] for _ in range(n)]
        errs = np.empty(n, dtype=object)
        errs[:] = None
        for i, msg in decode_errs.items():
            errs[i] = msg
        for (row, off), resp in zip(spans, resps):
            if resp is None:
                continue
            if not resp.ok:
                errs[row] = f"{resp.status_code} {resp.reason}"
                continue
            try:
                seg = resp.json()
            except (ValueError, json.JSONDecodeError):
                seg = None
            if isinstance(seg, dict):
                seg = dict(seg)
                seg["StreamOffsetMs"] = off
                per_row[row].append(seg)

        if self.flatten_results:
            rows, segs = [], []
            for i, lst in enumerate(per_row):
                for seg in lst:
                    rows.append(i)
                    segs.append(seg)
            out = np.empty(len(segs), dtype=object)
            for j, s in enumerate(segs):
                out[j] = s
            flat = table.take(np.asarray(rows, np.int64))
            return flat.with_column(self.output_col, out)

        out = np.empty(n, dtype=object)
        for i, lst in enumerate(per_row):
            out[i] = lst
        result = table.with_column(self.output_col, out)
        if self.error_col:
            result = result.with_column(self.error_col, errs)
        return result

    def transform_schema(self, columns):
        return list(columns) + [self.output_col] + (
            [self.error_col] if self.error_col and not self.flatten_results
            else [])


@register_stage
class ConversationTranscription(SpeechToTextSDK):
    """Multi-speaker conversation transcription: the same windowed audio
    streaming as SpeechToTextSDK against the conversation-transcription
    endpoint, with the service's speaker attribution passed through on
    every utterance (reference SpeechToTextSDK.scala ConversationTranscription
    variant — there a different SDK recognizer class, same emitted schema
    plus speakerId)."""

    _path = ("/speech/recognition/conversation/cognitiveservices/v1"
             "?transcriptionMode=conversation")
