"""AzureSearchWriter: index creation + batched document push with backoff.

Reference: cognitive/AzureSearch.scala (348 LoC) + AzureSearchAPI.scala
(199 LoC) — ensure the index exists, then POST documents in batches; on
throttling/partial failure split the batch and retry with exponential
backoff.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

import numpy as np

from ..core.schema import Table
from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData

__all__ = ["AzureSearchWriter"]


class AzureSearchWriter:
    API_VERSION = "2019-05-06"

    def __init__(self, service_name: str = "", index_name: str = "",
                 key: str = "", index_definition: Optional[dict] = None,
                 batch_size: int = 100, base_url: Optional[str] = None,
                 max_retries: int = 4):
        self.index_name = index_name or (index_definition or {}).get("name", "")
        self.key = key
        self.index_definition = index_definition
        self.batch_size = int(batch_size)
        self.max_retries = int(max_retries)
        self.base_url = (base_url or
                         f"https://{service_name}.search.windows.net")

    def _headers(self) -> Dict[str, str]:
        return {"Content-Type": "application/json", "api-key": self.key}

    def ensure_index(self) -> bool:
        """Create the index if a definition was given (createIndexIfNotExists,
        AzureSearchAPI.scala)."""
        if not self.index_definition:
            return True
        url = (f"{self.base_url}/indexes/{self.index_name}"
               f"?api-version={self.API_VERSION}")
        resp = send_request(HTTPRequestData(
            url=url, method="PUT", headers=self._headers(),
            entity=json.dumps(self.index_definition).encode(),
        ))
        return resp.ok or resp.status_code == 409  # already exists

    def _push(self, docs: List[dict]) -> int:
        url = (f"{self.base_url}/indexes/{self.index_name}/docs/index"
               f"?api-version={self.API_VERSION}")
        resp = send_request(HTTPRequestData(
            url=url, method="POST", headers=self._headers(),
            entity=json.dumps({"value": docs}).encode(),
        ))
        return resp.status_code

    def write(self, table: Table, action: str = "upload") -> int:
        """Push every row as a document; returns documents written.

        Batches split + exponential backoff on 207/429/503 (the reference's
        retryWithBackoff over batch bisection)."""
        if not self.ensure_index():
            raise RuntimeError("index creation failed")
        docs = []
        for row in table.rows():
            doc = {}
            for k, v in row.items():
                if isinstance(v, np.ndarray):
                    v = v.tolist()
                elif isinstance(v, np.generic):
                    v = v.item()
                doc[k] = v
            doc["@search.action"] = action
            docs.append(doc)

        written = 0
        stack: List[tuple] = [(docs[i: i + self.batch_size], 0)
                              for i in range(0, len(docs), self.batch_size)]
        while stack:
            batch, attempt = stack.pop()
            if not batch:
                continue
            status = self._push(batch)
            if status in (200, 201):
                written += len(batch)
            elif status in (207, 429, 503) and attempt < self.max_retries:
                time.sleep(0.05 * (2 ** attempt))
                if len(batch) > 1:
                    mid = len(batch) // 2
                    stack.append((batch[:mid], attempt + 1))
                    stack.append((batch[mid:], attempt + 1))
                else:
                    stack.append((batch, attempt + 1))
            else:
                raise RuntimeError(
                    f"azure search push failed with status {status}"
                )
        return written
