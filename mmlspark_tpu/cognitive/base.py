"""Cognitive service base: config-driven HTTP transformer stages.

Reference: cognitive/CognitiveServiceBase.scala:29-322 — `ServiceParam`
scalar-or-column params, URL/entity preparation, subscription-key header,
`getInternalTransformer` = Lambda -> SimpleHTTPTransformer -> DropColumns;
plus BasicAsyncReply (ComputerVision.scala) — async polling on the
Operation-Location header until status succeeded/failed.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.params import Param, ServiceParam, TypeConverters
from ..core.pipeline import Transformer
from ..core.schema import Table
from ..io.http.clients import (AsyncHTTPClient, CircuitBreaker,
                               HandlingUtils, get_breaker, get_shared_client)
from ..io.http.schema import HTTPRequestData, HTTPResponseData

__all__ = ["CognitiveServicesBase", "BasicAsyncReply"]


class CognitiveServicesBase(Transformer):
    """One service = one subclass declaring `_path` + payload preparation.

    Every service accepts constant params or per-row columns (ServiceParam),
    posts JSON (or binary) to `url`, and emits parsed JSON + an error column.
    """

    subscription_key = ServiceParam("service API key", default=None)
    url = Param("full endpoint URL (overrides location routing)", default="")
    location = Param("azure region used to build the default URL",
                     default="eastus")
    output_col = Param("parsed response column", default="output")
    error_col = Param("error column", default="errors")
    concurrency = Param("max in-flight requests", default=4,
                        converter=TypeConverters.to_int)
    timeout = Param("per-request timeout (s)", default=60.0,
                    converter=TypeConverters.to_float)
    breaker_threshold = Param(
        "circuit breaker: consecutive retryable failures before the "
        "endpoint's shared circuit opens (0 disables — the default)",
        default=0, converter=TypeConverters.to_int)
    breaker_reset_s = Param(
        "circuit breaker: seconds an open circuit waits before admitting "
        "a half-open probe", default=30.0,
        converter=TypeConverters.to_float)

    _path = ""  # subclass: service URL path
    _domain = "api.cognitive.microsoft.com"

    def _base_url(self) -> str:
        if self.url:
            return self.url
        return f"https://{self.location}.{self._domain}{self._path}"

    # ---- subclass surface -------------------------------------------------
    def _prepare_entity(self, table: Table, i: int) -> Optional[bytes]:
        """JSON body for row i (None -> skip the row: null output)."""
        raise NotImplementedError

    def _prepare_url(self, table: Table, i: int) -> str:
        return self._base_url()

    def _prepare_method(self) -> str:
        return "POST"

    def _headers(self, table: Table, i: int) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        key = self.resolve("subscription_key", table, i)
        if key:
            h["Ocp-Apim-Subscription-Key"] = str(key)
        return h

    def _postprocess(self, resp: HTTPResponseData) -> Any:
        try:
            return resp.json()
        except (ValueError, json.JSONDecodeError):
            return None

    # ---- driver -----------------------------------------------------------
    def _client(self) -> AsyncHTTPClient:
        return get_shared_client(int(self.concurrency), float(self.timeout))

    def _breaker(self) -> Optional[CircuitBreaker]:
        """Per-HOST shared breaker (all stages hitting the same endpoint
        pool their failure budget), or None when disabled."""
        if int(self.breaker_threshold) <= 0:
            return None
        from urllib.parse import urlsplit

        host = urlsplit(self._base_url()).netloc or self._base_url()
        return get_breaker(host, int(self.breaker_threshold),
                           float(self.breaker_reset_s))

    def _transform(self, table: Table) -> Table:
        n = len(table)
        reqs: List[Optional[HTTPRequestData]] = []
        for i in range(n):
            entity = self._prepare_entity(table, i)
            if entity is None:
                reqs.append(None)
                continue
            reqs.append(HTTPRequestData(
                url=self._prepare_url(table, i),
                method=self._prepare_method(),
                headers=self._headers(table, i),
                entity=entity,
            ))
        client = self._client()
        resps = client.send_all(reqs, breaker=self._breaker())
        # post-handling (e.g. async-operation polling) runs through the same
        # bounded pool: rows poll concurrently, not one-after-another
        resps = list(client._pool.map(
            lambda pair: self._handle_response(pair[1], table, pair[0]),
            enumerate(resps),
        ))
        out = np.empty(n, dtype=object)
        errs = np.empty(n, dtype=object)
        for i, r in enumerate(resps):
            if r is None:
                out[i] = None
                errs[i] = None
            elif r.ok:
                out[i] = self._postprocess(r)
                errs[i] = None
            else:
                out[i] = None
                errs[i] = f"{r.status_code} {r.reason}"
        result = table.with_column(self.output_col, out)
        if self.error_col:
            result = result.with_column(self.error_col, errs)
        return result

    def _handle_response(self, resp, table, i):
        return resp


class BasicAsyncReply(CognitiveServicesBase):
    """Async-operation services: the first POST returns 202 + an
    Operation-Location URL polled until success (ComputerVision.scala
    BasicAsyncReply)."""

    polling_interval_ms = Param("poll interval", default=300,
                                converter=TypeConverters.to_int)
    max_polls = Param("max polls before giving up", default=100,
                      converter=TypeConverters.to_int)

    def _handle_response(self, resp, table, i):
        if resp is None or resp.status_code not in (200, 201, 202):
            return resp
        loc = resp.headers.get("Operation-Location") or resp.headers.get(
            "operation-location"
        )
        if not loc:
            return resp
        poll_req = HTTPRequestData(url=loc, method="GET",
                                   headers=self._headers(table, i))
        breaker = self._breaker()
        for attempt in range(int(self.max_polls)):
            if attempt:  # first status check is immediate
                time.sleep(float(self.polling_interval_ms) / 1000.0)
            poll = HandlingUtils.advanced(poll_req, timeout=float(self.timeout),
                                          breaker=breaker)
            if not poll.ok:
                return poll
            try:
                status = str(poll.json().get("status", "")).lower()
            except (ValueError, json.JSONDecodeError):
                return poll
            if status in ("succeeded", "failed", "partiallycompleted",
                          "cancelled", "validationfailed"):
                return poll
        return HTTPResponseData(408, "async operation polling exhausted")
