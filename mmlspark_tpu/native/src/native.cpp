// Native host runtime for mmlspark_tpu: the C++ pieces the reference keeps
// native (SURVEY §2.9) rebuilt for the TPU host side.
//
//  - murmur3 batch hashing        <- VW murmur feature hashing
//    (vw/VowpalWabbitMurmurWithPrefix.scala bridges to native VW murmur)
//  - GBDT histogram accumulation  <- LightGBM's C++ histogram kernels
//    (the host-side fallback/reference for the XLA histogram path)
//  - numeric CSV parsing          <- fast columnar ingestion for the
//    data-loader path (BinaryFileFormat/CSV ingestion is JVM-side there)
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this image).
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>

extern "C" {

// ----------------------------------------------------------- murmur3 x86_32
static inline uint32_t rotl32(uint32_t x, int8_t r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16; h *= 0x85ebca6b;
    h ^= h >> 13; h *= 0xc2b2ae35;
    h ^= h >> 16;
    return h;
}

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
    const int64_t nblocks = len / 4;
    uint32_t h1 = seed;
    const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;
    for (int64_t i = 0; i < nblocks; i++) {
        uint32_t k1;
        std::memcpy(&k1, data + 4 * i, 4);
        k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2;
        h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
    }
    const uint8_t* tail = data + nblocks * 4;
    uint32_t k1 = 0;
    switch (len & 3) {
        case 3: k1 ^= tail[2] << 16; [[fallthrough]];
        case 2: k1 ^= tail[1] << 8;  [[fallthrough]];
        case 1: k1 ^= tail[0];
                k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
    }
    h1 ^= (uint32_t)len;
    return fmix32(h1);
}

// Hash n strings packed into `data` with prefix-sum `offsets` (n+1 entries).
void murmur3_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                   uint32_t seed, uint32_t* out) {
    for (int64_t i = 0; i < n; i++) {
        out[i] = murmur3_32(data + offsets[i], offsets[i + 1] - offsets[i],
                            seed);
    }
}

// ------------------------------------------------- GBDT histogram building
// bins: (n_rows, n_features) uint8 pre-binned features (row-major)
// grad/hess: (n_rows,); node_idx: (n_rows,) int32 leaf assignment (-1 skip)
// out: (n_nodes, n_features, n_bins, 2) float64 accumulating (grad, hess)
void histogram_f64(const uint8_t* bins, const float* grad, const float* hess,
                   const int32_t* node_idx, int64_t n_rows,
                   int64_t n_features, int64_t n_bins, int64_t n_nodes,
                   double* out) {
    const int64_t node_stride = n_features * n_bins * 2;
    for (int64_t r = 0; r < n_rows; r++) {
        const int32_t node = node_idx[r];
        if (node < 0 || node >= n_nodes) continue;
        const double g = grad[r], h = hess[r];
        const uint8_t* row = bins + r * n_features;
        double* base = out + node * node_stride;
        for (int64_t f = 0; f < n_features; f++) {
            double* cell = base + (f * n_bins + row[f]) * 2;
            cell[0] += g;
            cell[1] += h;
        }
    }
}

// ------------------------------------------------------ numeric CSV parser
// Parse a CSV of doubles (no quoting) into a row-major buffer.
// Returns rows parsed, or -1 on open failure, -2 on column mismatch.
// First call with out=NULL to count rows/cols (returned via n_rows/n_cols).
int64_t csv_count(const char* path, int64_t* n_rows, int64_t* n_cols,
                  int has_header) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    int64_t rows = 0, cols = 0;
    int c, line_cols = 1, in_line = 0, line_no = 0;
    while ((c = std::fgetc(f)) != EOF) {
        if (c == '\n') {
            if (in_line) {
                if (line_no >= has_header) {
                    if (cols == 0) cols = line_cols;
                    else if (line_cols != cols) { std::fclose(f); return -2; }
                    rows++;
                }
                line_no++;
            }
            line_cols = 1; in_line = 0;
        } else {
            in_line = 1;
            if (c == ',') line_cols++;
        }
    }
    if (in_line) {
        if (line_no >= has_header) {
            if (cols == 0) cols = line_cols;
            rows++;
        }
    }
    std::fclose(f);
    *n_rows = rows; *n_cols = cols;
    return rows;
}

int64_t csv_parse(const char* path, int has_header, double* out,
                  int64_t max_vals) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return -1;
    char* line = nullptr;
    size_t cap = 0;  // getline grows the buffer: arbitrary line width
    int64_t written = 0, line_no = 0;
    while (getline(&line, &cap, f) != -1) {
        if (line_no++ < has_header) continue;
        char* p = line;
        if (*p == '\n' || *p == '\0') continue;
        while (true) {
            char* end = nullptr;
            double v = std::strtod(p, &end);
            if (written >= max_vals) { std::free(line); std::fclose(f); return -3; }
            if (end == p) { std::free(line); std::fclose(f); return -4; }
            out[written++] = v;
            p = end;
            while (*p && *p != ',' && *p != '\n') p++;
            if (*p != ',') break;
            p++;
        }
    }
    std::free(line);
    std::fclose(f);
    return written;
}


// --------------------------------------------------------------- JPEG decode
// Native JPEG path for the image ingestion hot loop (the reference's OpenCV
// imdecode, opencv/.../ImageTransformer.scala decode modes) — libjpeg(-turbo)
// when the build found jpeglib.h, otherwise the entry point reports
// unavailable (-2) and Python stays on the PIL fallback.  scale_denom gives
// the 1/2, 1/4, 1/8 DCT-domain decodes for thumbnail-bound pipelines.
#ifdef MML_HAVE_JPEG
}  // extern "C"  (jpeglib.h must not be wrapped in extern "C" twice)
#include <jpeglib.h>
#include <jerror.h>
#include <csetjmp>
extern "C" {

namespace {
struct MmlJpegErr {
    jpeg_error_mgr pub;
    jmp_buf jb;
    bool truncated;
};

void mml_jpeg_error_exit(j_common_ptr cinfo) {
    longjmp(reinterpret_cast<MmlJpegErr*>(cinfo->err)->jb, 1);
}

void mml_jpeg_emit(j_common_ptr cinfo, int msg_level) {
    // no stderr spam; PIL-parity: only truncation (premature EOF) rejects
    // the image — benign warnings (extraneous marker bytes etc.) decode
    // fine everywhere and must not force a PIL re-decode
    if (msg_level == -1 && cinfo->err->msg_code == JWRN_JPEG_EOF) {
        reinterpret_cast<MmlJpegErr*>(cinfo->err)->truncated = true;
    }
}

void mml_jpeg_silence(j_common_ptr) {}

void mml_jpeg_init_err(jpeg_decompress_struct* cinfo, MmlJpegErr* jerr) {
    cinfo->err = jpeg_std_error(&jerr->pub);
    jerr->pub.error_exit = mml_jpeg_error_exit;
    jerr->pub.emit_message = mml_jpeg_emit;
    jerr->pub.output_message = mml_jpeg_silence;
    jerr->truncated = false;
}
}  // namespace

// Output dims/channels after scaling; 0 ok, -1 bad stream.
int32_t mml_jpeg_probe(const uint8_t* data, int64_t len, int32_t scale_denom,
                       int32_t* h, int32_t* w, int32_t* c) {
    jpeg_decompress_struct cinfo;
    MmlJpegErr jerr;
    mml_jpeg_init_err(&cinfo, &jerr);
    if (setjmp(jerr.jb)) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    cinfo.scale_num = 1;
    cinfo.scale_denom = scale_denom > 0 ? scale_denom : 1;
    jpeg_calc_output_dimensions(&cinfo);
    *h = static_cast<int32_t>(cinfo.output_height);
    *w = static_cast<int32_t>(cinfo.output_width);
    *c = cinfo.jpeg_color_space == JCS_GRAYSCALE ? 1 : 3;
    jpeg_destroy_decompress(&cinfo);
    return 0;
}

// Decode to HWC uint8, BGR channel order (gray stays 1 channel).
// 0 ok; -1 bad stream; -3 out buffer too small.
int32_t mml_jpeg_decode_bgr(const uint8_t* data, int64_t len,
                            int32_t scale_denom, uint8_t* out,
                            int64_t out_cap, int32_t* h, int32_t* w,
                            int32_t* c) {
    jpeg_decompress_struct cinfo;
    MmlJpegErr jerr;
    mml_jpeg_init_err(&cinfo, &jerr);
    if (setjmp(jerr.jb)) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    cinfo.scale_num = 1;
    cinfo.scale_denom = scale_denom > 0 ? scale_denom : 1;
    bool gray = cinfo.jpeg_color_space == JCS_GRAYSCALE;
    bool native_bgr = false;
    if (gray) {
        cinfo.out_color_space = JCS_GRAYSCALE;
    } else {
#ifdef JCS_EXTENSIONS
        cinfo.out_color_space = JCS_EXT_BGR;  // libjpeg-turbo: free swizzle
        native_bgr = true;
#else
        cinfo.out_color_space = JCS_RGB;
#endif
    }
    jpeg_start_decompress(&cinfo);
    const int32_t W = cinfo.output_width, H = cinfo.output_height;
    const int32_t C = gray ? 1 : 3;
    if (static_cast<int64_t>(W) * H * C > out_cap) {
        jpeg_abort_decompress(&cinfo);
        jpeg_destroy_decompress(&cinfo);
        return -3;
    }
    const int64_t stride = static_cast<int64_t>(W) * C;
    while (cinfo.output_scanline < cinfo.output_height) {
        uint8_t* row = out + static_cast<int64_t>(cinfo.output_scanline) * stride;
        JSAMPROW rows[1] = {row};
        jpeg_read_scanlines(&cinfo, rows, 1);
        if (!gray && !native_bgr) {
            for (int64_t x = 0; x < W; x++) {  // RGB -> BGR in place
                uint8_t t = row[3 * x];
                row[3 * x] = row[3 * x + 2];
                row[3 * x + 2] = t;
            }
        }
    }
    *h = H;
    *w = W;
    *c = C;
    jpeg_finish_decompress(&cinfo);
    // libjpeg treats truncated data as a recoverable warning and pads
    // gray: reject it like PIL does, or garbage rows would silently enter
    // training data (benign warnings still decode)
    bool corrupt = jerr.truncated;
    jpeg_destroy_decompress(&cinfo);
    return corrupt ? -1 : 0;
}

#else  // !MML_HAVE_JPEG

int32_t mml_jpeg_probe(const uint8_t*, int64_t, int32_t, int32_t*, int32_t*,
                       int32_t*) {
    return -2;  // built without libjpeg
}

int32_t mml_jpeg_decode_bgr(const uint8_t*, int64_t, int32_t, uint8_t*,
                            int64_t, int32_t*, int32_t*, int32_t*) {
    return -2;
}

#endif  // MML_HAVE_JPEG

}  // extern "C"
