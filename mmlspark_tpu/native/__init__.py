"""Native host runtime: ctypes bindings to libmmlspark_native.so.

Reference: the four external C++ engines bridged via JNI/SWIG (SURVEY §2.9)
and their `NativeLoader` (extract .so + System.load).  Here the native lib is
built from mmlspark_tpu/native/src/native.cpp on first use (g++ is part of
the toolchain) and loaded with ctypes; every entry point has a NumPy
fallback so the framework stays functional without a compiler.

Surface:
  available()                 -> bool (lib built + loaded)
  murmur3_batch(strs, seed)   -> uint32 hashes (VW murmur parity)
  histogram(bins, g, h, node) -> GBDT gradient/hessian histograms
  load_csv_numeric(path)      -> float64 matrix (fast columnar ingestion)
  decode_jpeg_bgr(bytes)      -> HWC uint8 BGR array (libjpeg fast path,
                                 DCT-domain 1/2..1/8 scale_denom decodes)
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Union

import numpy as np

__all__ = ["available", "build", "murmur3_batch", "histogram",
           "load_csv_numeric", "decode_jpeg_bgr", "decode_jpeg_bgr_into",
           "jpeg_probe", "jpeg_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libmmlspark_native.so")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

# same default ceiling as PIL's DecompressionBombError threshold
MAX_JPEG_PIXELS = 178_956_970


def build(force: bool = False) -> bool:
    """Compile the shared lib (make -C mmlspark_tpu/native).  Always runs
    make (a no-op when fresh) so a stale .so picks up new entry points."""
    try:
        subprocess.run(
            ["make", "-C", _DIR] + (["-B"] if force else []),
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_SO)
    except (subprocess.SubprocessError, FileNotFoundError):
        return os.path.exists(_SO)


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.murmur3_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_uint32, ctypes.c_void_p,
        ]
        lib.histogram_f64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.csv_count.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        lib.csv_count.restype = ctypes.c_int64
        lib.csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.csv_parse.restype = ctypes.c_int64
        for fn in ("mml_jpeg_probe", "mml_jpeg_decode_bgr"):
            if hasattr(lib, fn):
                getattr(lib, fn).restype = ctypes.c_int32
        if hasattr(lib, "mml_jpeg_probe"):
            lib.mml_jpeg_probe.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.mml_jpeg_decode_bgr.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


def murmur3_batch(strings: Sequence[Union[str, bytes]],
                  seed: int = 0) -> np.ndarray:
    """Hash a batch of strings; bit-exact with online.hashing.murmurhash3_32."""
    blobs = [s.encode("utf-8") if isinstance(s, str) else bytes(s)
             for s in strings]
    lib = _load()
    if lib is None:  # NumPy-free Python fallback
        from ..online.hashing import murmurhash3_32

        return np.array([murmurhash3_32(b, seed) for b in blobs], np.uint32)
    data = b"".join(blobs)
    offsets = np.zeros(len(blobs) + 1, np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    out = np.zeros(len(blobs), np.uint32)
    buf = np.frombuffer(data, np.uint8) if data else np.zeros(1, np.uint8)
    lib.murmur3_batch(
        buf.ctypes.data, offsets.ctypes.data, len(blobs),
        ctypes.c_uint32(seed & 0xFFFFFFFF), out.ctypes.data,
    )
    return out


def histogram(bins: np.ndarray, grad: np.ndarray, hess: np.ndarray,
              node_idx: np.ndarray, n_nodes: int,
              n_bins: int = 256) -> np.ndarray:
    """(n_nodes, n_features, n_bins, 2) gradient/hessian histograms.

    bins: (n, f) uint8; node_idx: (n,) int32, -1 = skip row.
    """
    bins = np.ascontiguousarray(bins, np.uint8)
    grad = np.ascontiguousarray(grad, np.float32)
    hess = np.ascontiguousarray(hess, np.float32)
    node_idx = np.ascontiguousarray(node_idx, np.int32)
    n, f = bins.shape
    out = np.zeros((n_nodes, f, n_bins, 2), np.float64)
    lib = _load()
    if lib is None:
        for node in range(n_nodes):
            mask = node_idx == node
            for j in range(f):
                np.add.at(out[node, j, :, 0], bins[mask, j], grad[mask])
                np.add.at(out[node, j, :, 1], bins[mask, j], hess[mask])
        return out
    lib.histogram_f64(
        bins.ctypes.data, grad.ctypes.data, hess.ctypes.data,
        node_idx.ctypes.data, n, f, n_bins, n_nodes, out.ctypes.data,
    )
    return out


def load_csv_numeric(path: str, has_header: bool = True) -> np.ndarray:
    """Parse a numeric CSV into a float64 (rows, cols) matrix."""
    lib = _load()
    if lib is None:
        return np.loadtxt(path, delimiter=",", dtype=np.float64,
                          skiprows=1 if has_header else 0, ndmin=2)
    n_rows = ctypes.c_int64()
    n_cols = ctypes.c_int64()
    rc = lib.csv_count(path.encode(), ctypes.byref(n_rows),
                       ctypes.byref(n_cols), int(has_header))
    if rc == -1:
        raise FileNotFoundError(path)
    if rc < 0:
        raise ValueError(f"ragged CSV: {path}")
    r, c = n_rows.value, n_cols.value
    out = np.zeros(r * c, np.float64)
    written = lib.csv_parse(path.encode(), int(has_header),
                            out.ctypes.data, r * c)
    if written == -4:
        raise ValueError(f"non-numeric cell in CSV: {path}")
    if written != r * c:
        raise ValueError(f"CSV parse mismatch: {written} != {r * c}")
    return out.reshape(r, c)


def jpeg_available() -> bool:
    """True when the lib was built against libjpeg (probe returns != -2)."""
    lib = _load()
    if lib is None or not hasattr(lib, "mml_jpeg_probe"):
        return False
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    c = ctypes.c_int32()
    # 2-byte garbage: -1 (bad stream) means jpeg code is compiled in; -2 not
    buf = np.frombuffer(b"xx", np.uint8)
    rc = lib.mml_jpeg_probe(buf.ctypes.data, 2, 1, ctypes.byref(h),
                            ctypes.byref(w), ctypes.byref(c))
    return rc != -2


def decode_jpeg_bgr(data: bytes, scale_denom: int = 1) -> Optional[np.ndarray]:
    """Decode JPEG bytes to an HWC uint8 array in BGR order (gray: 1
    channel); None when the native path is unavailable or the stream is
    invalid.  `scale_denom` in {1,2,4,8} decodes at reduced resolution in
    the DCT domain — the cheap path when the target size is far below the
    source (ImageTransformer decode modes, SURVEY §2.6).

    The GIL is released during the C call, so a ThreadPoolExecutor over
    this function scales decode across host cores.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "mml_jpeg_decode_bgr"):
        return None
    buf = np.frombuffer(data, np.uint8)
    if len(buf) == 0:
        return None
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    c = ctypes.c_int32()
    rc = lib.mml_jpeg_probe(buf.ctypes.data, len(buf), int(scale_denom),
                            ctypes.byref(h), ctypes.byref(w), ctypes.byref(c))
    if rc != 0:
        return None
    # decompression-bomb guard (PIL's Image.MAX_IMAGE_PIXELS analog): the
    # dims come from an untrusted header; don't allocate gigabytes for them
    if h.value * w.value > MAX_JPEG_PIXELS:
        return None
    out = np.empty(h.value * w.value * c.value, np.uint8)
    rc = lib.mml_jpeg_decode_bgr(buf.ctypes.data, len(buf), int(scale_denom),
                                 out.ctypes.data, out.nbytes,
                                 ctypes.byref(h), ctypes.byref(w),
                                 ctypes.byref(c))
    if rc != 0:
        return None
    return out.reshape(h.value, w.value, c.value)


def jpeg_probe(data: bytes, scale_denom: int = 1):
    """Header-only (h, w, c) of a JPEG stream (~µs, no pixel decode) — lets
    callers group rows by output shape and preallocate batch buffers before
    any decode.  None when unavailable/invalid/bomb-sized."""
    lib = _load()
    if lib is None or not hasattr(lib, "mml_jpeg_probe"):
        return None
    buf = np.frombuffer(data, np.uint8)
    if len(buf) == 0:
        return None
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    c = ctypes.c_int32()
    rc = lib.mml_jpeg_probe(buf.ctypes.data, len(buf), int(scale_denom),
                            ctypes.byref(h), ctypes.byref(w), ctypes.byref(c))
    if rc != 0 or h.value * w.value > MAX_JPEG_PIXELS:
        return None
    return (h.value, w.value, c.value)


def decode_jpeg_bgr_into(data: bytes, out: np.ndarray,
                         scale_denom: int = 1) -> bool:
    """Decode JPEG bytes directly into a preallocated HWC uint8 view (e.g.
    one image slot of a [N,H,W,C] batch buffer) — no intermediate array, no
    stack copy.  `out` must be C-contiguous and exactly match the decoded
    (h, w, c).  Returns False on any mismatch or decode failure (caller
    falls back / drops the row)."""
    lib = _load()
    if lib is None or not hasattr(lib, "mml_jpeg_decode_bgr"):
        return False
    if not out.flags["C_CONTIGUOUS"] or out.dtype != np.uint8:
        raise ValueError("decode_jpeg_bgr_into: need C-contiguous uint8 out")
    buf = np.frombuffer(data, np.uint8)
    if len(buf) == 0:
        return False
    h = ctypes.c_int32()
    w = ctypes.c_int32()
    c = ctypes.c_int32()
    rc = lib.mml_jpeg_decode_bgr(buf.ctypes.data, len(buf), int(scale_denom),
                                 out.ctypes.data, out.nbytes,
                                 ctypes.byref(h), ctypes.byref(w),
                                 ctypes.byref(c))
    return rc == 0 and out.shape == (h.value, w.value, c.value)
