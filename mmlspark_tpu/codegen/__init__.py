"""Binding autogenerator (reference: core codegen/, L7)."""
from .generate import camel, generate_r_wrappers, generate_tests, generate_wrappers

__all__ = ["generate_wrappers", "generate_tests", "generate_r_wrappers",
           "camel"]
