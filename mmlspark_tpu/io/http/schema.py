"""HTTP request/response as typed row values.

Reference: core io/http/HTTPSchema.scala:36-348 — full HTTP request/response
StructTypes with SparkBindings codecs (`HTTPRequestData`, `HTTPResponseData`,
entity/headers/status) and the `to_http_request` SQL helpers.

Here the codecs are dataclasses <-> plain dicts; Table columns hold the
dataclass instances (object columns), mirroring the reference's struct rows.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

__all__ = ["HTTPRequestData", "HTTPResponseData", "to_http_request"]


@dataclass
class HTTPRequestData:
    url: str
    method: str = "POST"
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    def to_dict(self) -> dict:
        return {
            "url": self.url,
            "method": self.method,
            "headers": dict(self.headers),
            "entity": self.entity.decode("utf-8", "replace")
            if self.entity is not None else None,
        }

    @staticmethod
    def from_dict(d: dict) -> "HTTPRequestData":
        e = d.get("entity")
        return HTTPRequestData(
            url=d["url"], method=d.get("method", "POST"),
            headers=dict(d.get("headers") or {}),
            entity=e.encode() if isinstance(e, str) else e,
        )


@dataclass
class HTTPResponseData:
    status_code: int
    reason: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    @property
    def ok(self) -> bool:
        return 200 <= self.status_code < 300

    def json(self) -> Any:
        return json.loads(self.entity or b"null")

    def text(self) -> str:
        return (self.entity or b"").decode("utf-8", "replace")

    def to_dict(self) -> dict:
        return {
            "status_code": self.status_code,
            "reason": self.reason,
            "headers": dict(self.headers),
            "entity": self.entity.decode("utf-8", "replace")
            if self.entity is not None else None,
        }


def to_http_request(url: str, payload: Any, method: str = "POST",
                    headers: Optional[Dict[str, str]] = None) -> HTTPRequestData:
    """JSON-encode a payload into a request row (HTTPSchema.scala
    to_http_request analog)."""
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    return HTTPRequestData(
        url=url, method=method, headers=hdrs,
        entity=json.dumps(payload).encode("utf-8"),
    )
