"""HTTP-on-tables: request/response schema, clients, transformer stages.

Reference: core io/http (~2.8k LoC: HTTPSchema.scala, Clients.scala,
HTTPClients.scala, HTTPTransformer.scala, SimpleHTTPTransformer.scala,
Parsers.scala, SharedVariable.scala).
"""
from .clients import AsyncHTTPClient, HandlingUtils, send_request
from .schema import HTTPRequestData, HTTPResponseData, to_http_request
from .transformers import (
    CustomInputParser,
    CustomOutputParser,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    SimpleHTTPTransformer,
    StringOutputParser,
)

__all__ = [
    "HTTPRequestData",
    "HTTPResponseData",
    "to_http_request",
    "send_request",
    "HandlingUtils",
    "AsyncHTTPClient",
    "HTTPTransformer",
    "SimpleHTTPTransformer",
    "JSONInputParser",
    "CustomInputParser",
    "JSONOutputParser",
    "StringOutputParser",
    "CustomOutputParser",
]
