"""HTTP clients: retry/backoff handling + bounded-concurrency async pipeline.

Reference: core io/http/HTTPClients.scala:65-156 (`HandlingUtils.advanced`
retry-with-backoff incl. 429 Retry-After) and Clients.scala:48-120
(`AsyncClient`: bounded-concurrency Future pipeline with ordered results).

Host-side only (urllib + thread pool) — the data plane between client and
device is Table columns, exactly like the reference's executor-side Apache
HttpClient pools.
"""
from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional, Sequence

from ...core import telemetry
from ...utils.faults import fault_point
from .schema import HTTPRequestData, HTTPResponseData

__all__ = ["send_request", "HandlingUtils", "AsyncHTTPClient",
           "get_shared_client", "CircuitBreaker", "get_breaker"]


class CircuitBreaker:
    """Per-endpoint circuit breaker: closed → open after
    `failure_threshold` CONSECUTIVE retryable failures → (after
    `reset_timeout_s`) half-open, admitting exactly ONE probe at a time —
    probe success closes the circuit, probe failure re-opens it.

    While open, callers get a synthesized local 503 ("circuit open",
    Retry-After = seconds until the next probe window) without touching
    the network — the point is to stop hammering an endpoint that is
    down and to fail fast instead of burning the full retry/backoff
    ladder per request.  Opt-in: nothing constructs one unless asked
    (`AsyncHTTPClient(breaker=...)`, `get_breaker(host)`).

    Transitions are counted in core.telemetry: ``circuit.open``,
    ``circuit.half_open_probe``, ``circuit.closed`` (plus per-name
    variants), so a soak can assert the breaker actually cycled."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.name = str(name)
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock  # injectable for deterministic tests
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request proceed right now?  A half-open `True` claims
        the single probe slot — the caller MUST follow with record()."""
        with self._lock:
            if self._state == "closed":
                return True
            if (self._state == "open"
                    and self._clock() - self._opened_at
                    >= self.reset_timeout_s):
                self._state = "half_open"
            if self._state == "half_open" and not self._probing:
                self._probing = True
                telemetry.incr("circuit.half_open_probe")
                telemetry.incr(f"circuit.half_open_probe.{self.name}")
                return True
            return False

    def retry_after_s(self) -> float:
        """Seconds until the next probe window (0 when not open)."""
        with self._lock:
            if self._state != "open":
                return 0.0
            return max(0.0, self.reset_timeout_s
                       - (self._clock() - self._opened_at))

    def record(self, ok: bool) -> None:
        with self._lock:
            was = self._state
            self._probing = False
            if ok:
                self._failures = 0
                self._state = "closed"
                if was != "closed":
                    telemetry.incr("circuit.closed")
                    telemetry.incr(f"circuit.closed.{self.name}")
                return
            self._failures += 1
            if was == "half_open" or self._failures >= self.failure_threshold:
                self._state = "open"
                self._opened_at = self._clock()
                self._failures = 0
                if was != "open":
                    telemetry.incr("circuit.open")
                    telemetry.incr(f"circuit.open.{self.name}")


_BREAKERS: dict = {}
_BREAKERS_LOCK = threading.Lock()


def get_breaker(name: str, failure_threshold: int = 5,
                reset_timeout_s: float = 30.0) -> CircuitBreaker:
    """Process-shared breaker registry keyed by name (conventionally the
    endpoint host) — every client/transformer hitting the same endpoint
    shares one failure budget, like get_shared_client shares one pool.
    Config arguments apply only on first construction."""
    with _BREAKERS_LOCK:
        br = _BREAKERS.get(name)
        if br is None:
            br = _BREAKERS[name] = CircuitBreaker(
                name, failure_threshold, reset_timeout_s)
        return br


def _circuit_open_response(breaker: CircuitBreaker) -> HTTPResponseData:
    return HTTPResponseData(
        status_code=503, reason="circuit open",
        headers={"Retry-After": f"{breaker.retry_after_s():.3f}",
                 "X-Circuit": breaker.name},
    )


def send_request(req: HTTPRequestData, timeout: float = 60.0) -> HTTPResponseData:
    """One HTTP exchange; transport errors become status 0 / reason text.

    Trace propagation: when the calling thread is inside a span, the
    current context rides out as `X-Trace-Id`/`X-Span-Id` (caller-set
    headers win), the downstream server continues the trace, and the
    exchange is recorded as an `http.send` child span here."""
    headers = telemetry.trace_headers(req.headers)
    ctx = telemetry.current_context()
    r = urllib.request.Request(
        req.url, data=req.entity, headers=headers, method=req.method,
    )
    t0 = time.perf_counter()
    try:
        fault_point("http.send")
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            out = HTTPResponseData(
                status_code=resp.status, reason=resp.reason or "",
                headers=dict(resp.headers.items()), entity=resp.read(),
            )
    except urllib.error.HTTPError as e:
        out = HTTPResponseData(
            status_code=e.code, reason=str(e.reason),
            headers=dict(e.headers.items()) if e.headers else {},
            entity=e.read(),
        )
    except Exception as e:  # URLError, timeout, connection refused...
        out = HTTPResponseData(status_code=0,
                               reason=f"{type(e).__name__}: {e}")
    dt = time.perf_counter() - t0
    telemetry.histogram("io.http.request.latency").observe(dt)
    if ctx is not None:
        telemetry.record_span("http.send", ctx, dt,
                              url=req.url, status=out.status_code)
    return out


class HandlingUtils:
    """Retry policies (HTTPClients.scala HandlingUtils.advanced)."""

    RETRYABLE = frozenset({0, 408, 429, 500, 502, 503, 504})

    @staticmethod
    def advanced(req: HTTPRequestData, backoffs_ms: Sequence[int] = (100, 500, 1000),
                 timeout: float = 60.0,
                 breaker: Optional[CircuitBreaker] = None) -> HTTPResponseData:
        """Send with retries: exponential backoff list; 429 honors
        Retry-After; non-retryable statuses return immediately.  With a
        `breaker`, every attempt first asks the circuit: an open circuit
        short-circuits to a local 503 (no network, no backoff ladder),
        and each attempt's outcome feeds the breaker's failure count."""
        if breaker is not None and not breaker.allow():
            return _circuit_open_response(breaker)
        resp = send_request(req, timeout)
        if breaker is not None:
            breaker.record(resp.status_code not in HandlingUtils.RETRYABLE)
        for backoff in backoffs_ms:
            if resp.status_code not in HandlingUtils.RETRYABLE:
                return resp
            wait_s = backoff / 1000.0
            if resp.status_code == 429:
                ra = resp.headers.get("Retry-After") or resp.headers.get(
                    "retry-after"
                )
                if ra is not None:
                    try:
                        wait_s = max(float(ra), wait_s)
                    except ValueError:
                        pass
            time.sleep(wait_s)
            if breaker is not None and not breaker.allow():
                return _circuit_open_response(breaker)
            resp = send_request(req, timeout)
            if breaker is not None:
                breaker.record(
                    resp.status_code not in HandlingUtils.RETRYABLE)
        return resp

    @staticmethod
    def basic(req: HTTPRequestData, timeout: float = 60.0) -> HTTPResponseData:
        return send_request(req, timeout)


class AsyncHTTPClient:
    """Bounded-concurrency request pipeline with ORDERED results.

    Reference: Clients.scala:48 AsyncClient — requests are dispatched up to
    `concurrency` at a time; results come back in submission order.
    """

    def __init__(self, concurrency: int = 8, timeout: float = 60.0,
                 backoffs_ms: Sequence[int] = (100, 500, 1000),
                 breaker: Optional[CircuitBreaker] = None):
        self.concurrency = int(concurrency)
        self.timeout = float(timeout)
        self.backoffs_ms = tuple(backoffs_ms)
        self.breaker = breaker  # opt-in; see CircuitBreaker/get_breaker
        self._pool = ThreadPoolExecutor(max_workers=self.concurrency)

    def send(self, req: HTTPRequestData,
             breaker: Optional[CircuitBreaker] = None) -> HTTPResponseData:
        return HandlingUtils.advanced(
            req, self.backoffs_ms, self.timeout,
            breaker=breaker if breaker is not None else self.breaker)

    def send_all(self, requests: Iterable[Optional[HTTPRequestData]],
                 breaker: Optional[CircuitBreaker] = None,
                 ) -> List[Optional[HTTPResponseData]]:
        """None requests yield None responses (null-safe, like the
        reference's sendRequestsWithContext).  `breaker` overrides the
        instance breaker for this batch — the hook cognitive services
        use to route calls through their per-host shared breaker without
        forking the process-shared client."""

        def one(req):
            if req is None:
                return None
            return self.send(req, breaker=breaker)

        return list(self._pool.map(one, requests))

    def close(self):
        self._pool.shutdown(wait=False)


def get_shared_client(concurrency: int, timeout: float) -> AsyncHTTPClient:
    """Process-shared client keyed by config (SharedVariable semantics) —
    the one place the cache key is built, used by HTTPTransformer and every
    cognitive service."""
    from ...core.shared import shared_singleton

    key = ("AsyncHTTPClient", int(concurrency), float(timeout))
    return shared_singleton(
        key, lambda: AsyncHTTPClient(int(concurrency), float(timeout))
    )
