"""HTTP clients: retry/backoff handling + bounded-concurrency async pipeline.

Reference: core io/http/HTTPClients.scala:65-156 (`HandlingUtils.advanced`
retry-with-backoff incl. 429 Retry-After) and Clients.scala:48-120
(`AsyncClient`: bounded-concurrency Future pipeline with ordered results).

Host-side only (urllib + thread pool) — the data plane between client and
device is Table columns, exactly like the reference's executor-side Apache
HttpClient pools.
"""
from __future__ import annotations

import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional, Sequence

from .schema import HTTPRequestData, HTTPResponseData

__all__ = ["send_request", "HandlingUtils", "AsyncHTTPClient",
           "get_shared_client"]


def send_request(req: HTTPRequestData, timeout: float = 60.0) -> HTTPResponseData:
    """One HTTP exchange; transport errors become status 0 / reason text."""
    r = urllib.request.Request(
        req.url, data=req.entity, headers=req.headers or {},
        method=req.method,
    )
    try:
        with urllib.request.urlopen(r, timeout=timeout) as resp:
            return HTTPResponseData(
                status_code=resp.status, reason=resp.reason or "",
                headers=dict(resp.headers.items()), entity=resp.read(),
            )
    except urllib.error.HTTPError as e:
        return HTTPResponseData(
            status_code=e.code, reason=str(e.reason),
            headers=dict(e.headers.items()) if e.headers else {},
            entity=e.read(),
        )
    except Exception as e:  # URLError, timeout, connection refused...
        return HTTPResponseData(status_code=0, reason=f"{type(e).__name__}: {e}")


class HandlingUtils:
    """Retry policies (HTTPClients.scala HandlingUtils.advanced)."""

    RETRYABLE = frozenset({0, 408, 429, 500, 502, 503, 504})

    @staticmethod
    def advanced(req: HTTPRequestData, backoffs_ms: Sequence[int] = (100, 500, 1000),
                 timeout: float = 60.0) -> HTTPResponseData:
        """Send with retries: exponential backoff list; 429 honors
        Retry-After; non-retryable statuses return immediately."""
        resp = send_request(req, timeout)
        for backoff in backoffs_ms:
            if resp.status_code not in HandlingUtils.RETRYABLE:
                return resp
            wait_s = backoff / 1000.0
            if resp.status_code == 429:
                ra = resp.headers.get("Retry-After") or resp.headers.get(
                    "retry-after"
                )
                if ra is not None:
                    try:
                        wait_s = max(float(ra), wait_s)
                    except ValueError:
                        pass
            time.sleep(wait_s)
            resp = send_request(req, timeout)
        return resp

    @staticmethod
    def basic(req: HTTPRequestData, timeout: float = 60.0) -> HTTPResponseData:
        return send_request(req, timeout)


class AsyncHTTPClient:
    """Bounded-concurrency request pipeline with ORDERED results.

    Reference: Clients.scala:48 AsyncClient — requests are dispatched up to
    `concurrency` at a time; results come back in submission order.
    """

    def __init__(self, concurrency: int = 8, timeout: float = 60.0,
                 backoffs_ms: Sequence[int] = (100, 500, 1000)):
        self.concurrency = int(concurrency)
        self.timeout = float(timeout)
        self.backoffs_ms = tuple(backoffs_ms)
        self._pool = ThreadPoolExecutor(max_workers=self.concurrency)

    def send(self, req: HTTPRequestData) -> HTTPResponseData:
        return HandlingUtils.advanced(req, self.backoffs_ms, self.timeout)

    def send_all(self, requests: Iterable[Optional[HTTPRequestData]]
                 ) -> List[Optional[HTTPResponseData]]:
        """None requests yield None responses (null-safe, like the
        reference's sendRequestsWithContext)."""

        def one(req):
            if req is None:
                return None
            return self.send(req)

        return list(self._pool.map(one, requests))

    def close(self):
        self._pool.shutdown(wait=False)


def get_shared_client(concurrency: int, timeout: float) -> AsyncHTTPClient:
    """Process-shared client keyed by config (SharedVariable semantics) —
    the one place the cache key is built, used by HTTPTransformer and every
    cognitive service."""
    from ...core.shared import shared_singleton

    key = ("AsyncHTTPClient", int(concurrency), float(timeout))
    return shared_singleton(
        key, lambda: AsyncHTTPClient(int(concurrency), float(timeout))
    )
