"""HTTP transformer stages: request columns -> async HTTP -> response columns.

Reference: core io/http/HTTPTransformer.scala:86-141 (mapPartitions +
SharedVariable client), SimpleHTTPTransformer.scala:64 (InputParser ->
HTTPTransformer -> OutputParser pipeline with optional error column), and
Parsers.scala:26-231 (JSONInputParser, CustomInputParser, JSONOutputParser,
StringOutputParser, CustomOutputParser).
"""
from __future__ import annotations

import json
import numpy as np

from ...core.params import ComplexParam, Param, TypeConverters
from ...core.pipeline import Transformer
from ...core.registry import register_stage
from ...core.schema import Table, find_unused_column_name
from .clients import AsyncHTTPClient, get_shared_client
from .schema import HTTPRequestData, HTTPResponseData

__all__ = [
    "HTTPTransformer",
    "SimpleHTTPTransformer",
    "JSONInputParser",
    "CustomInputParser",
    "JSONOutputParser",
    "StringOutputParser",
    "CustomOutputParser",
]


@register_stage
class HTTPTransformer(Transformer):
    """Column of HTTPRequestData -> column of HTTPResponseData, sent through
    the process-shared bounded-concurrency client."""

    input_col = Param("request column", default="request")
    output_col = Param("response column", default="response")
    concurrency = Param("max in-flight requests", default=8,
                        converter=TypeConverters.to_int)
    timeout = Param("per-request timeout (s)", default=60.0,
                    converter=TypeConverters.to_float)

    def _client(self) -> AsyncHTTPClient:
        return get_shared_client(int(self.concurrency), float(self.timeout))

    def _transform(self, table: Table) -> Table:
        reqs = [
            r if isinstance(r, (HTTPRequestData, type(None)))
            else HTTPRequestData.from_dict(r)
            for r in table[self.input_col]
        ]
        resps = self._client().send_all(reqs)
        out = np.empty(len(table), dtype=object)
        for i, r in enumerate(resps):
            out[i] = r
        return table.with_column(self.output_col, out)


@register_stage
class JSONInputParser(Transformer):
    """Rows -> JSON POST requests (Parsers.scala JSONInputParser)."""

    input_cols = Param("columns to serialize into the JSON body", default=None,
                       converter=TypeConverters.to_list_str)
    output_col = Param("request column", default="request")
    url = Param("target URL", default="")
    method = Param("HTTP method", default="POST")
    headers = ComplexParam("extra headers dict", default=None)

    def _transform(self, table: Table) -> Table:
        cols = self.get_or_default("input_cols") or table.column_names
        hdrs = {"Content-Type": "application/json"}
        hdrs.update(self.get_or_default("headers") or {})
        out = np.empty(len(table), dtype=object)
        data = {c: table[c] for c in cols}
        for i in range(len(table)):
            payload = {
                c: (v.tolist() if isinstance(v := data[c][i], np.ndarray) else
                    v.item() if isinstance(v, np.generic) else v)
                for c in cols
            }
            out[i] = HTTPRequestData(
                url=self.url, method=self.method, headers=dict(hdrs),
                entity=json.dumps(payload).encode("utf-8"),
            )
        return table.with_column(self.output_col, out)


@register_stage
class CustomInputParser(Transformer):
    """row dict -> HTTPRequestData via a user function."""

    input_cols = Param("columns passed to the udf", default=None,
                       converter=TypeConverters.to_list_str)
    output_col = Param("request column", default="request")
    udf = ComplexParam("callable(row_dict) -> HTTPRequestData")

    def _transform(self, table: Table) -> Table:
        cols = self.get_or_default("input_cols") or table.column_names
        fn = self.udf
        out = np.empty(len(table), dtype=object)
        data = {c: table[c] for c in cols}
        for i in range(len(table)):
            out[i] = fn({c: data[c][i] for c in cols})
        return table.with_column(self.output_col, out)


@register_stage
class JSONOutputParser(Transformer):
    """HTTPResponseData -> parsed JSON value column (Parsers.scala
    JSONOutputParser); non-2xx or bad JSON -> None."""

    input_col = Param("response column", default="response")
    output_col = Param("parsed output column", default="output")

    def _transform(self, table: Table) -> Table:
        out = np.empty(len(table), dtype=object)
        for i, r in enumerate(table[self.input_col]):
            if isinstance(r, HTTPResponseData) and r.ok:
                try:
                    out[i] = r.json()
                except (ValueError, json.JSONDecodeError):
                    out[i] = None
            else:
                out[i] = None
        return table.with_column(self.output_col, out)


@register_stage
class StringOutputParser(Transformer):
    input_col = Param("response column", default="response")
    output_col = Param("text output column", default="output")

    def _transform(self, table: Table) -> Table:
        out = np.empty(len(table), dtype=object)
        for i, r in enumerate(table[self.input_col]):
            out[i] = r.text() if isinstance(r, HTTPResponseData) else None
        return table.with_column(self.output_col, out)


@register_stage
class CustomOutputParser(Transformer):
    input_col = Param("response column", default="response")
    output_col = Param("parsed output column", default="output")
    udf = ComplexParam("callable(HTTPResponseData) -> value")

    def _transform(self, table: Table) -> Table:
        fn = self.udf
        out = np.empty(len(table), dtype=object)
        for i, r in enumerate(table[self.input_col]):
            out[i] = fn(r) if r is not None else None
        return table.with_column(self.output_col, out)


@register_stage
class SimpleHTTPTransformer(Transformer):
    """input parser -> HTTPTransformer -> output parser, with an optional
    error column for failed exchanges (SimpleHTTPTransformer.scala:64)."""

    input_parser = ComplexParam("input parser Transformer", default=None)
    output_parser = ComplexParam("output parser Transformer", default=None)
    input_cols = Param("columns for the default JSON input parser",
                       default=None, converter=TypeConverters.to_list_str)
    output_col = Param("parsed output column", default="output")
    url = Param("target URL (default JSON parser)", default="")
    error_col = Param("error detail column ('' = raise-free null outputs)",
                      default="errors")
    concurrency = Param("max in-flight requests", default=8,
                        converter=TypeConverters.to_int)
    timeout = Param("per-request timeout (s)", default=60.0,
                    converter=TypeConverters.to_float)

    def _transform(self, table: Table) -> Table:
        req_col = find_unused_column_name("request", table.column_names)
        resp_col = find_unused_column_name("response", table.column_names)
        in_parser = self.get_or_default("input_parser") or JSONInputParser(
            input_cols=self.get_or_default("input_cols"), url=self.url,
        )
        in_parser = in_parser.copy({"output_col": req_col})
        out_parser = self.get_or_default("output_parser") or JSONOutputParser()
        out_parser = out_parser.copy(
            {"input_col": resp_col, "output_col": self.output_col}
        )
        http = HTTPTransformer(
            input_col=req_col, output_col=resp_col,
            concurrency=int(self.concurrency), timeout=float(self.timeout),
        )
        t = http.transform(in_parser.transform(table))
        result = out_parser.transform(t)
        err_col = self.error_col
        if err_col:
            errs = np.empty(len(table), dtype=object)
            for i, r in enumerate(t[resp_col]):
                if isinstance(r, HTTPResponseData) and not r.ok:
                    errs[i] = f"{r.status_code} {r.reason}"
                else:
                    errs[i] = None
            result = result.with_column(err_col, errs)
        return result.drop(req_col, resp_col)
