"""PowerBIWriter: batched row push to a PowerBI streaming-dataset REST URL.

Reference: io/powerbi/PowerBIWriter.scala:27 (batched POST of row groups
through the HTTP retry stack).
"""
from __future__ import annotations

import json
import numpy as np

from ..core.schema import Table
from .http.clients import HandlingUtils
from .http.schema import HTTPRequestData

__all__ = ["write_to_power_bi"]


def write_to_power_bi(table: Table, url: str, batch_size: int = 100,
                      timeout: float = 60.0) -> int:
    """POST rows as JSON arrays in batches; returns rows written.

    Raises RuntimeError on a non-retryable failure (after the standard
    backoff policy, incl. 429 Retry-After handling).
    """
    def jsonable(v):
        if isinstance(v, np.ndarray):
            v = v.tolist()
        if isinstance(v, (list, tuple)):
            return [jsonable(x) for x in v]
        if isinstance(v, dict):
            return {k: jsonable(x) for k, x in v.items()}
        if isinstance(v, np.generic):
            v = v.item()
        # bare NaN/Infinity are invalid JSON — the endpoint would 400
        if isinstance(v, float) and not np.isfinite(v):
            return None
        return v

    rows = []
    for row in table.rows():
        rows.append({k: jsonable(v) for k, v in row.items()})
    written = 0
    for lo in range(0, len(rows), batch_size):
        batch = rows[lo: lo + batch_size]
        resp = HandlingUtils.advanced(HTTPRequestData(
            url=url, method="POST",
            headers={"Content-Type": "application/json"},
            entity=json.dumps(batch).encode(),
        ), timeout=timeout)
        if not resp.ok:
            raise RuntimeError(
                f"PowerBI push failed at batch {lo // batch_size}: "
                f"{resp.status_code} {resp.reason}"
            )
        written += len(batch)
    return written
