"""Image IO: decode bytes <-> image rows.

Reference: core io/image/ImageUtils.scala:26-165 (decode bytes ->
BufferedImage -> Spark image row and back; `safeRead` tolerant decode) and
org/apache/spark/ml/source/image/PatchedImageFileFormat.scala.

An "image row" is a dict with the Spark image schema fields
(origin, height, width, nChannels, mode, data) where `data` is an
HWC uint8 ndarray in **BGR** channel order (OpenCV/Spark convention).
"""
from __future__ import annotations

import io as _io
from typing import Any, Dict, Optional

import numpy as np

from ..core.schema import Table

__all__ = [
    "decode_image",
    "encode_image_row",
    "safe_read",
    "image_row_to_array",
    "array_to_image_row",
    "read_image_dir",
    "read_binary_files",
]

OCV_8UC1 = 0
OCV_8UC3 = 16
OCV_8UC4 = 24


def array_to_image_row(arr: np.ndarray, origin: str = "") -> Dict[str, Any]:
    arr = np.asarray(arr, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    h, w, c = arr.shape
    mode = {1: OCV_8UC1, 3: OCV_8UC3, 4: OCV_8UC4}[c]
    return {"origin": origin, "height": h, "width": w, "nChannels": c,
            "mode": mode, "data": arr}


def image_row_to_array(row: Dict[str, Any]) -> np.ndarray:
    data = row["data"]
    if isinstance(data, (bytes, bytearray)):
        data = np.frombuffer(data, dtype=np.uint8)
    arr = np.asarray(data, dtype=np.uint8)
    return arr.reshape(row["height"], row["width"], row["nChannels"])


def decode_image(data: bytes, origin: str = "") -> Dict[str, Any]:
    """Decode compressed bytes (png/jpeg/bmp/...) to a BGR image row.

    JPEGs take the native libjpeg path (BGR swizzle in the decoder, GIL
    released — the OpenCV-imdecode analog, SURVEY §2.6/§2.9); everything
    else, and any native failure, goes through PIL."""
    if data[:3] == b"\xff\xd8\xff":
        from .. import native

        arr = native.decode_jpeg_bgr(data)
        if arr is not None:
            return array_to_image_row(arr, origin)
    from PIL import Image

    img = Image.open(_io.BytesIO(data))
    if img.mode not in ("RGB", "L", "RGBA"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 3 and arr.shape[2] >= 3:
        arr = arr[:, :, :3][:, :, ::-1]  # RGB(A) -> BGR
    return array_to_image_row(arr, origin)


def safe_read(data: Optional[bytes], origin: str = "") -> Optional[Dict[str, Any]]:
    """Tolerant decode: None on failure (ImageUtils.safeRead)."""
    if data is None:
        return None
    try:
        return decode_image(data, origin)
    except Exception:  # noqa: BLE001 — by contract: any decode failure -> None
        return None


def encode_image_row(row: Dict[str, Any], fmt: str = "PNG") -> bytes:
    from PIL import Image

    arr = image_row_to_array(row)
    if arr.shape[2] == 3:
        arr = arr[:, :, ::-1]  # BGR -> RGB
    elif arr.shape[2] == 1:
        arr = arr[:, :, 0]
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format=fmt)
    return buf.getvalue()


def read_binary_files(pattern: str, recursive: bool = True,
                      sample_ratio: float = 1.0) -> Table:
    """(path, bytes) table from a glob — BinaryFileFormat analog; delegates
    to the canonical threaded reader in io/binary.py."""
    from .binary import read_binary_files as _impl

    return _impl(pattern, recursive=recursive, sample_ratio=sample_ratio)


def read_image_dir(pattern: str, drop_invalid: bool = True) -> Table:
    """Image-source analog (PatchedImageFileFormat.scala:154): decode every
    file under the glob into an `image` column of image rows."""
    files = read_binary_files(pattern)
    rows = [safe_read(b, origin=p) for p, b in zip(files["path"], files["bytes"])]
    t = Table({"image": rows})
    if drop_invalid:
        mask = np.array([r is not None for r in rows])
        t = t.filter(mask)
    return t
