from .feed import (DeviceFeed, FeedTelemetry, FEED_TELEMETRY, FeedSource,
                   FEED_END, default_depth)
from .pipeline import (HostPipeline, PipelineStage, PipelineTelemetry,
                       PIPELINE_TELEMETRY, pipeline_workers)
