from .feed import DeviceFeed, FeedTelemetry, FEED_TELEMETRY, default_depth
