"""Sharded direct-to-chip transfers: the h2d wall attacked head-on.

PR 2/7/12 made the feed *overlap* perfectly — and BENCH_LASTGOOD still
says `e2e_bound: "h2d"` at 0.058 GB/s against an 11.2k img/s forward,
because a single monolithic `device_put` serializes the whole batch
through one staging buffer and one transfer stream.  This module attacks
the transfer itself (ROADMAP, first open item):

  * **Per-shard puts.**  A host batch bound for a `NamedSharding` is
    split along its shard boundaries (``sharding.
    addressable_devices_indices_map`` — the generalization of
    SNIPPETS.md [2]'s ``get_naive_sharding``/``shard_params`` pattern)
    and each sub-array rides its OWN ``jax.device_put(slice, device)``
    straight into that chip's addressable shard; the global array is
    assembled zero-copy with ``jax.make_array_from_single_device_arrays``.
    One transfer stream per chip instead of one for the host.
  * **A per-device transfer pool.**  Shard copies dispatch concurrently
    on a process-wide pool of one worker per addressable device
    (daemon threads ``feed-shard-<i>``, bounded task queue) — the link
    is parallel hardware; feeding it serially was the bug.
  * **Pre-pinned, size-bucketed staging.**  Shard slices are copied
    into reusable power-of-two-bucketed staging buffers before dispatch
    (replacing the feed's single monolithic ring slot for this path).
    Buffers are fenced on their device arrays before reuse and live for
    the process, so steady state does no allocation on real chips.  The
    CPU backend's ``device_put`` aliases host memory zero-copy for the
    LIFE of the device array, so there staged buffers are discarded
    instead of recycled (`_host_aliasing`) — a fence orders a transfer,
    it cannot un-alias memory.
  * **The ladder underneath.**  Every per-shard put crosses the
    `feed.shard_put` fault point behind a `core.flow.StagePolicy`
    retry rung; a shard that exhausts its retries raises
    `ShardTransferError` and the owning `DeviceFeed` degrades the
    group (then the engine) to the coalesced single-put path — the
    existing degrade ladder, one rung higher.  Chaos coverage:
    tests/test_shard_put.py + `tools/chaos_soak.py --flow`.

Telemetry rides the declared `io.feed.shard.*` series; per-shard
bandwidth lands in `FeedTelemetry` (`shard_gbps`,
`transfer_concurrency` in `tools/feed_bench.py --sharded`).
See docs/performance.md ("Demolishing the h2d wall").
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import telemetry as core_telemetry
from ..utils.faults import fault_point
from ..utils.sync import make_lock

__all__ = ["ShardEngine", "ShardTransferError", "StagingBuckets",
           "transfer_pool", "shard_layout"]

_BUCKET_MIN = 1 << 16  # smallest staging bucket: 64 KiB


class ShardTransferError(Exception):
    """A shard transfer failed after its full retry ladder; the caller
    (DeviceFeed) degrades the group to the coalesced path."""


# ---------------------------------------------------------------------------
# The per-device transfer pool: one worker per addressable device, shared
# process-wide (transfers from every DeviceFeed instance ride it).
# ---------------------------------------------------------------------------
class _Task:
    """One submitted transfer: callable + completion latch.  Hand-rolled
    (not concurrent.futures) so the queue stays bounded and the shared
    state is lockset-visible to graftsan."""

    __slots__ = ("fn", "result", "error", "done")

    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


class _TransferPool:
    """Bounded pool of `workers` daemon transfer threads.  Submissions
    block when the task queue is full (backpressure, never unbounded
    memory); `run_all` dispatches a group and waits for every member,
    re-raising the first error AFTER all have settled so no shard's
    device buffer is abandoned mid-flight."""

    def __init__(self, workers: int):
        self.workers = max(1, int(workers))
        self._q: "queue.Queue[_Task]" = queue.Queue(maxsize=4 * self.workers)
        self._lock = make_lock("io.feed.shard.pool")
        self._inflight = 0  #: guarded-by self._lock
        self._inflight_hw = 0  #: guarded-by self._lock
        for i in range(self.workers):
            threading.Thread(target=self._work, daemon=True,
                             name=f"feed-shard-{i}").start()

    def _work(self):
        while True:
            task = self._q.get()
            try:
                task.result = task.fn()
            except BaseException as e:  # noqa: BLE001 — relayed to submitter
                task.error = e
            finally:
                with self._lock:
                    self._inflight -= 1
                task.done.set()

    def submit(self, fn: Callable[[], Any]) -> _Task:
        task = _Task(fn)
        with self._lock:
            self._inflight += 1
            if self._inflight > self._inflight_hw:
                self._inflight_hw = self._inflight
        self._q.put(task)
        core_telemetry.gauge("io.feed.shard.queue.depth").set(
            self._q.qsize())
        return task

    def concurrency_high_water(self) -> int:
        with self._lock:
            return self._inflight_hw

    def run_all(self, fns: List[Callable[[], Any]]) -> List[Any]:
        tasks = [self.submit(fn) for fn in fns]
        for t in tasks:
            t.done.wait()
        for t in tasks:
            if t.error is not None:
                raise t.error
        return [t.result for t in tasks]


_POOL_LOCK = make_lock("io.feed.shard.pool_registry")
_POOL: Dict[str, _TransferPool] = {}  #: guarded-by _POOL_LOCK


def transfer_pool(workers: Optional[int] = None) -> _TransferPool:
    """The process-wide transfer pool, lazily sized to the addressable
    device count (or `workers` on first call).  One pool for every feed:
    the link's parallelism is a host resource, not a per-consumer one."""
    with _POOL_LOCK:
        pool = _POOL.get("pool")
        if pool is None:
            if workers is None:
                import jax

                workers = max(1, len(jax.local_devices()))
            pool = _TransferPool(workers)
            _POOL["pool"] = pool
        return pool


# ---------------------------------------------------------------------------
# Size-bucketed staging buffers (the "pre-pinned" host side of the path).
# ---------------------------------------------------------------------------
def _bucket_size(nbytes: int) -> int:
    b = _BUCKET_MIN
    while b < nbytes:
        b <<= 1
    return b


class _StagingBuf:
    __slots__ = ("buf", "fence")

    def __init__(self, nbytes: int):
        self.buf = np.empty(nbytes, np.uint8)
        self.fence: Any = None  # device arrays to block on before reuse


class StagingBuckets:
    """Reusable power-of-two-bucketed host staging buffers.

    `acquire(nbytes)` hands out a buffer of the next bucket size up
    (free-listed per bucket; steady state allocates nothing) and
    `release(buf, fence)` returns it carrying the device arrays whose
    transfers must complete before the bytes may be rewritten —
    `device_put` can alias host memory zero-copy on the CPU backend, so
    reuse is fenced exactly like the feed's ring slots.  On a real chip
    the runtime pins these stable host pages for DMA, which is the
    other half of why reuse (not reallocation) matters."""

    def __init__(self, max_per_bucket: int = 16):
        self.max_per_bucket = int(max_per_bucket)
        self._lock = make_lock("io.feed.shard.staging")
        self._free: Dict[int, List[_StagingBuf]] = {}  #: guarded-by self._lock
        self._allocated = 0  #: guarded-by self._lock

    def discard(self, sb: _StagingBuf) -> None:
        """Drop a buffer whose bytes now BACK a live device array (the
        CPU backend's zero-copy `device_put` alias): it must never
        re-enter a free list — a fence orders the transfer but cannot
        un-alias the memory."""
        with self._lock:
            self._allocated -= 1

    def acquire(self, nbytes: int) -> _StagingBuf:
        size = _bucket_size(nbytes)
        with self._lock:
            free = self._free.get(size)
            if free:
                sb = free.pop()
            else:
                sb = _StagingBuf(size)
                self._allocated += 1
        if sb.fence is not None:
            import jax

            jax.block_until_ready(sb.fence)
            sb.fence = None
        return sb

    def release(self, sb: _StagingBuf, fence: Any = None) -> None:
        sb.fence = fence
        with self._lock:
            self._free.setdefault(len(sb.buf), []).append(sb)
            # bound the pool: beyond max_per_bucket the oldest buffer is
            # dropped to the allocator (bursts must not pin memory forever)
            if len(self._free[len(sb.buf)]) > self.max_per_bucket:
                self._free[len(sb.buf)].pop(0)

    def allocated(self) -> int:
        with self._lock:
            return self._allocated


# ---------------------------------------------------------------------------
# The engine.
# ---------------------------------------------------------------------------
def _host_aliasing() -> bool:
    """True when this backend's `device_put` may alias host memory
    zero-copy for the life of the device array (the CPU backend) rather
    than DMA-copying into device HBM.  Staged buffers must then be
    discarded, never recycled — rewriting one would rewrite the shard
    it backs (tests/test_shard_put.py proves the corruption without
    this gate)."""
    import jax

    return jax.default_backend() == "cpu"


def shard_layout(sharding, shape) -> Optional[List[Tuple[Any, tuple]]]:
    """[(device, index)] per addressable shard, or None when `shape`
    does not divide evenly — `parallel.mesh.addressable_shard_layout`,
    re-exported at the transfer engine's door.  This is SNIPPETS.md
    [2]'s naive-sharding pattern generalized: instead of one replicated
    `device_put` per leaf, every addressable shard gets its own direct
    transfer."""
    from ..parallel.mesh import addressable_shard_layout

    return addressable_shard_layout(sharding, shape)


class ShardEngine:
    """Concurrent per-shard `device_put` under a retry ladder.

    One engine per `DeviceFeed`; the transfer pool and staging buckets
    it uses are process-wide.  `put_sharded` raises
    `ShardTransferError` when any shard exhausts its retries — the
    owning feed degrades that group (and then itself) to the coalesced
    single-put path."""

    def __init__(self, policy=None, telemetry=None,
                 staging: Optional[StagingBuckets] = None,
                 min_shard_bytes: int = 1 << 12):
        from .feed import FEED_TELEMETRY

        self.policy = policy
        self.telemetry = telemetry if telemetry is not None else FEED_TELEMETRY
        self.staging = staging if staging is not None else _STAGING
        # below this per-shard size the fixed per-put cost dominates the
        # parallelism win; the caller should coalesce instead
        self.min_shard_bytes = int(min_shard_bytes)

    # ---- planning ------------------------------------------------------
    def plan(self, arr: np.ndarray, sharding) -> Optional[List[Tuple[Any, tuple]]]:
        """The shard layout when the sharded path applies: a real
        multi-device NamedSharding, an evenly-divisible batch, and
        shards big enough that per-put overhead stays amortized."""
        if sharding is None:
            return None
        layout = shard_layout(sharding, arr.shape)
        if layout is None or len(layout) <= 1:
            return None
        if arr.nbytes // len(layout) < self.min_shard_bytes:
            return None
        return layout

    # ---- the guarded per-shard put -------------------------------------
    def _put_shard(self, view: np.ndarray, device):
        """One shard's transfer: the `feed.shard_put` fault point behind
        the engine's StagePolicy retry rung; exhaustion surfaces as
        ShardTransferError for the feed's degrade rung."""
        import jax

        def attempt(v):
            fault_point("feed.shard_put")
            return jax.device_put(v, device)

        t0 = time.perf_counter()
        try:
            if self.policy is not None:
                out = self.policy.run(attempt, view)
            else:
                out = attempt(view)
        except Exception as e:  # noqa: BLE001 — mapped to the degrade rung
            raise ShardTransferError(
                f"shard transfer to {device} failed after retries: {e}"
            ) from e
        dt = time.perf_counter() - t0
        core_telemetry.incr("io.feed.shard.puts")
        core_telemetry.histogram("io.feed.shard.latency").observe(dt)
        core_telemetry.histogram(
            "io.feed.shard.bytes",
            boundaries=core_telemetry.BYTE_BUCKETS).observe(view.nbytes)
        return out, dt

    # ---- the sharded group put -----------------------------------------
    def put_sharded(self, arr: np.ndarray, sharding,
                    layout: Optional[List[Tuple[Any, tuple]]] = None):
        """`arr` -> one global jax.Array under `sharding`, moved as
        len(layout) concurrent direct-to-device transfers through the
        per-device pool, assembled without another copy."""
        import jax

        if layout is None:
            layout = self.plan(arr, sharding)
        if layout is None:
            raise ShardTransferError(
                f"shape {arr.shape} does not shard evenly under {sharding}")
        pool = transfer_pool()
        staged: List[Tuple[np.ndarray, Optional[_StagingBuf]]] = []
        for _dev, idx in layout:
            piece = arr[idx]
            if piece.flags["C_CONTIGUOUS"] and piece.base is None:
                # already its own contiguous buffer: stage-free
                staged.append((piece, None))
                continue
            sb = self.staging.acquire(piece.nbytes)
            view = sb.buf[:piece.nbytes].view(piece.dtype).reshape(piece.shape)
            np.copyto(view, piece)
            staged.append((view, sb))
        t0 = time.perf_counter()
        try:
            results = pool.run_all([
                (lambda v=view, d=dev: self._put_shard(v, d))
                for (dev, _idx), (view, _sb) in zip(layout, staged)])
        except ShardTransferError:
            for _view, sb in staged:
                if sb is not None:
                    self.staging.release(sb)
            raise
        wall = time.perf_counter() - t0
        shards = [r[0] for r in results]
        put_s = sum(r[1] for r in results)
        alias = _host_aliasing()
        for (_view, sb), shard in zip(staged, shards):
            if sb is None:
                continue
            if alias:
                self.staging.discard(sb)
            else:
                self.staging.release(sb, fence=shard)
        out = jax.make_array_from_single_device_arrays(
            arr.shape, sharding, shards)
        hw = pool.concurrency_high_water()
        self.telemetry.add(bytes_moved=arr.nbytes, transfer_calls=len(shards),
                           transfer_s=wall, shard_puts=len(shards),
                           shard_bytes=arr.nbytes, shard_wall_s=wall,
                           shard_put_s=put_s, sharded_groups=1)
        self.telemetry.note_max(transfer_concurrency=min(len(shards), hw))
        core_telemetry.gauge("io.feed.shard.concurrency").set(hw)
        return out


# process-wide staging buckets: the pinned pages are a host resource
_STAGING = StagingBuckets()
