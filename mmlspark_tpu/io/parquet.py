"""Parquet read/write for Tables.

The reference's pipelines live on parquet (every Spark DataFrame
checkpoint, the generated python fuzz fixtures — Fuzzing.scala:47-140
writes saved parquet fixtures); a user switching over needs their data
to load.  Arrow is the bridge: columnar both sides, so dense numeric
columns map zero-ish-copy, strings/bytes/lists round-trip through the
object dtype.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.schema import Table

__all__ = ["read_parquet", "write_parquet"]


def read_parquet(path: str, columns: Optional[List[str]] = None) -> Table:
    """One parquet file (or directory of row-group files) -> Table."""
    import pyarrow.parquet as pq

    at = pq.read_table(path, columns=columns)
    data = {}
    for name in at.column_names:
        col = at.column(name)
        np_col = col.to_numpy(zero_copy_only=False)
        if np_col.dtype.kind == "O":
            # list<...> columns arrive as object-of-ndarray already;
            # bytes/str stay objects — both are Table's ragged convention
            arr = np.empty(len(np_col), object)
            for i, v in enumerate(np_col):
                arr[i] = v
            np_col = arr
        data[name] = np_col
    return Table(data)


def write_parquet(table: Table, path: str) -> None:
    """Table -> one parquet file.  Dense numeric columns write as native
    arrow types; object columns become list/binary/string columns."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    cols, names = [], []
    for name in table.column_names:
        col = table[name]
        if col.dtype.kind == "O":
            cols.append(pa.array(list(col)))
        elif col.ndim > 1:
            # fixed-width matrices (feature vectors) write as lists —
            # the Spark VectorUDT-ish convention readers expect
            cols.append(pa.array(list(np.asarray(col))))
        else:
            cols.append(pa.array(col))
        names.append(name)
    pq.write_table(pa.Table.from_arrays(cols, names=names), path)
