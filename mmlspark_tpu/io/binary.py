"""Binary/CSV file ingestion: files -> columnar Table.

Reference: core io/binary/BinaryFileFormat.scala:112 (Hadoop-FS binary
DataSource producing (path, bytes) rows with sampleRatio push-down) +
BinaryFileReader.scala:20 (parallel read); CSV ingestion rides the native
C++ parser (mmlspark_tpu/native) instead of the JVM CSV stack.

This is THE binary reader; io/image.py's readers delegate here.
"""
from __future__ import annotations

import glob as _glob
import os
import random
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

import numpy as np

from ..core.schema import Table

__all__ = ["read_binary_files", "read_csv", "zip_iterator"]


def zip_iterator(path: str, sample_ratio: float = 1.0, seed: int = 0):
    """Yield (name, bytes) for every file entry of a zip archive, each
    name prefixed with the archive path (StreamUtilities.ZipIterator,
    core/env/StreamUtilities.scala:53-78): directories are skipped and
    `sample_ratio` Bernoulli-samples entries before extraction — the
    zipped-image-dataset ingestion path.
    """
    import zipfile

    rng = random.Random(seed)
    with zipfile.ZipFile(path) as zf:
        for info in zf.infolist():
            if info.is_dir() or rng.random() >= sample_ratio:
                continue
            data = zf.read(info)
            if len(data) != info.file_size:
                raise IOError(
                    f"short read from zip entry {info.filename}: "
                    f"{len(data)} of {info.file_size} bytes")
            yield os.path.join(path, info.filename), data


def read_binary_files(pattern: str, recursive: bool = True,
                      sample_ratio: float = 1.0, seed: int = 0,
                      num_threads: int = 8) -> Table:
    """Read every file matching `pattern` into a Table(path, bytes).

    `sample_ratio` subsamples the file list before any IO (the reference's
    sampleRatio push-down); reads are thread-parallel.
    """
    files = sorted(
        f for f in _glob.glob(pattern, recursive=recursive)
        if os.path.isfile(f)
    )
    if sample_ratio < 1.0:
        rng = random.Random(seed)
        files = [f for f in files if rng.random() < sample_ratio]

    def read(f):
        with open(f, "rb") as fh:
            return fh.read()

    with ThreadPoolExecutor(max_workers=num_threads) as pool:
        blobs = list(pool.map(read, files))
    data = np.empty(len(files), dtype=object)
    for i, b in enumerate(blobs):
        data[i] = b
    return Table({"path": np.array(files, dtype=object), "bytes": data})


def read_csv(path: str, has_header: bool = True,
             column_names: Optional[Sequence[str]] = None) -> Table:
    """Numeric CSV -> Table via the native C++ parser (NumPy fallback)."""
    from .. import native

    mat = native.load_csv_numeric(path, has_header=has_header)
    if column_names is None:
        if has_header:
            with open(path) as f:
                column_names = f.readline().strip().split(",")
        else:
            column_names = [f"c{i}" for i in range(mat.shape[1])]
    if len(column_names) != mat.shape[1]:
        raise ValueError(
            f"{len(column_names)} names for {mat.shape[1]} columns"
        )
    return Table({name: mat[:, i] for i, name in enumerate(column_names)})
