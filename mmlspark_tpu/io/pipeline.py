"""HostPipeline: the streaming input pipeline engine.

BENCH_r05 measured the gap this module closes: the ResNet-50 forward
sustains 11,167 images/sec while end-to-end ImageFeaturizer delivers
134.4 — the host stages (decode -> assemble -> h2d -> forward) ran
largely serially per batch, so e2e throughput was the SUM of stage
times instead of the MAX.  This is the pipelined-prefetch argument of
tf.data (Murray et al., VLDB 2021) and DALI's move-preprocessing-to-
accelerator design, applied to this stack.

Since the graftflow unification (core/flow.py) HostPipeline is a thin
adapter over the credit-based `FlowGraph` runtime — the same scheduler
that runs DeviceFeed's h2d hop and the ContinuousBatcher's admission
and prefill stages — keeping its historical surface:

  * **Stages with worker pools.**  A `HostPipeline` is an ordered list
    of `PipelineStage(name, fn, workers)` map stages.  Each stage owns
    `workers` threads pulling from a credit-bounded input queue; the
    decode codecs (libjpeg via `native`, PIL) release the GIL, so N
    decode workers decode N chunks concurrently while later stages and
    the device run ahead on earlier ones.
  * **Credit budgets = backpressure.**  Every stage boundary is bounded
    by the stage's credit budget; a slow device stalls assembly, which
    stalls decode — memory stays O(queue_size x chunk), never
    O(dataset).
  * **Order-preserving emission.**  Workers finish out of order; the
    runtime's per-stage reorder buffer re-emits results in sequence so
    chunk results land in feed order and the DeviceFeed's coalescer
    still sees same-shape runs back to back.
  * **Feeds DeviceFeed directly.**  `feed_source(items)` adapts the
    pipeline's ordered output to the feed engine's `FeedSource`
    protocol (io/feed.py), so decode of chunk N+2, h2d of N+1, and the
    forward of N are in flight simultaneously with no extra copy or
    hand-off thread in between.
  * **Telemetry.**  Per-stage busy seconds and item counts accumulate
    in `PIPELINE_TELEMETRY` (bench.py derives `decode_ms` /
    `host_assemble_ms` and the `e2e_bound` attribution from deltas);
    each item observes `io.pipeline.stage.latency{stage=...}`, queue
    depths mirror to `io.pipeline.queue.depth.<stage>` gauges (the
    legacy names, kept alongside the runtime's unified
    `flow.queue.depth.<stage>` / `flow.items.<stage>` series), and when
    the submitting thread is inside a trace every stage item records a
    `pipeline.<stage>` child span — `/trace/<id>` shows decode spans of
    later batches overlapping the transfer/forward of earlier ones.

Failure semantics are the runtime's: a stage exception (or a producer
exception) cancels the pipeline, and the consumer re-raises the
ORIGINAL error — no deadlock, no silent truncation.  All queue waits
are cancel-aware timeout loops, so an abandoned consumer (generator
closed early) or a dead consumer can never strand a worker.  See
docs/performance.md ("The input pipeline") and docs/robustness.md
("The flow runtime").
"""
from __future__ import annotations

import os
import queue
import threading
from typing import Any, Callable, Dict, Iterable, Optional, Sequence

from ..core import telemetry as core_telemetry
from ..core.flow import _EOF, Expired, FlowGraph, FlowItem, Stage
from ..utils.sync import make_lock
from .feed import FEED_END, FeedSource

__all__ = ["PipelineStage", "HostPipeline", "PipelineTelemetry",
           "PIPELINE_TELEMETRY", "pipeline_workers"]


def pipeline_workers(default: Optional[int] = None) -> int:
    """Decode/assembly worker count: MMLSPARK_PIPELINE_WORKERS overrides
    (the knob every adopter inherits); otherwise `default`, otherwise a
    conservative min(4, cores) — decode threads beyond the core count
    only add queue contention."""
    env = os.environ.get("MMLSPARK_PIPELINE_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if default is not None:
        return max(1, int(default))
    return max(1, min(4, os.cpu_count() or 2))


class PipelineTelemetry:
    """Thread-safe per-stage busy-seconds / item counters.

    `busy_s` for a stage is the sum of wall time its workers spend
    inside the stage fn — items/busy_s is the stage's standalone
    throughput bound, which is exactly what `e2e_bound` attribution
    needs (the pipeline's steady-state rate is min over stages of
    items/busy_s x workers)."""

    def __init__(self):
        self._lock = make_lock("io.pipeline.telemetry")
        self._stages: Dict[str, Dict[str, float]] = {}  #: guarded-by self._lock

    def add(self, stage: str, busy_s: float = 0.0, items: int = 0):
        with self._lock:
            rec = self._stages.setdefault(stage,
                                          {"busy_s": 0.0, "items": 0.0})
            rec["busy_s"] += busy_s
            rec["items"] += items

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stages.items()}

    def delta(self, since: Dict[str, Dict[str, float]]
              ) -> Dict[str, Dict[str, float]]:
        now = self.snapshot()
        out = {}
        for k, v in now.items():
            base = since.get(k, {})
            out[k] = {f: v[f] - base.get(f, 0.0) for f in v}
        return out


# process-wide default sink: bench.py and tests read deltas off this
PIPELINE_TELEMETRY = PipelineTelemetry()


class PipelineStage:
    """One map stage spec: `fn(value) -> value`, run by `workers`
    threads.  A plain spec holder, NOT a `core.flow.Stage` subclass —
    pipeline stage names are per-call dynamic (decode/assemble/...), so
    HostPipeline materializes anonymous base `Stage`s from these specs
    at construction (registered Stage subclasses must declare static
    names and budgets; see lint rule G405).

    `fn` must be thread-safe for workers > 1 (the decode/assembly fns
    here close over read-only inputs and write disjoint outputs)."""

    def __init__(self, name: str, fn: Callable[[Any], Any],
                 workers: int = 1):
        self.name = str(name)
        self.fn = fn
        self.workers = max(1, int(workers))


class HostPipeline:
    """Bounded multi-stage streaming pipeline over an item iterable —
    a thin wrapper binding the graftflow runtime (core/flow.py) to the
    historical io.pipeline surface and metric names.

    Drive it one of three ways:
      * `run(items)` — iterate the ordered final-stage outputs;
      * `feed_source(items)` — a `FeedSource` for `DeviceFeed.run`
        (the chunk path: stage outputs must be (chunk, n_valid) pairs);
      * `start(items)` + manual draining (tests).

    One pipeline instance is single-use (queues and counters are per
    run); instances are cheap — threads spawn at `start`."""

    def __init__(self, stages: Sequence[PipelineStage],
                 queue_size: Optional[int] = None,
                 telemetry: Optional[PipelineTelemetry] = None):
        if not stages:
            raise ValueError("HostPipeline needs at least one stage")
        self.stages = list(stages)
        self.telemetry = (telemetry if telemetry is not None
                          else PIPELINE_TELEMETRY)
        self._graph = FlowGraph(
            [Stage(name=s.name, fn=s.fn, workers=s.workers)
             for s in self.stages],
            queue_size=queue_size,
            span_prefix="pipeline",
            telemetry=self.telemetry,
            on_depth=self._mirror_depth,
            on_item=self._mirror_item,
            label="HostPipeline")
        self.queue_size = self._graph.queue_size

    # legacy metric names, alongside the runtime's flow.* series
    @staticmethod
    def _mirror_depth(name: str, depth: int) -> None:
        core_telemetry.gauge(f"io.pipeline.queue.depth.{name}").set(depth)

    @staticmethod
    def _mirror_item(name: str, seq: int, dt: float) -> None:
        core_telemetry.histogram("io.pipeline.stage.latency",
                                 stage=name).observe(dt)
        core_telemetry.incr(f"io.pipeline.items.{name}")

    # ---- lifecycle -----------------------------------------------------
    def start(self, items: Iterable[Any]):
        """Spawn the producer and every stage's workers (all daemon)."""
        self._graph.start(items)

    def cancel(self):
        """Stop all workers promptly; safe to call repeatedly."""
        self._graph.cancel()

    @property
    def error(self) -> Optional[BaseException]:
        return self._graph.error

    @property
    def _cancelled(self) -> threading.Event:
        return self._graph._cancelled

    def high_water(self) -> Dict[str, int]:
        """Max observed depth per hand-off queue (keyed by the stage the
        queue feeds, plus 'out') — the structural overlap witness: a
        stage queue that reached depth >= 2 had the previous stage
        running ahead while this one was still busy."""
        return self._graph.high_water()

    def _note_depth(self, name: str, depth: int) -> None:
        self._graph._note_depth(name, depth)

    # ---- consumption ---------------------------------------------------
    def _next_out(self, block: bool = True):
        """Next ordered (seq, value) from the out queue; `_EOF` at clean
        end; raises the pipeline's error, or queue.Empty when
        non-blocking and nothing is ready."""
        item = self._graph._next_out(block=block)
        if isinstance(item, _EOF):
            return item
        seq, payload = item
        if isinstance(payload, (FlowItem, Expired)):
            payload = payload.value
        return (seq, payload)

    def run(self, items: Iterable[Any]):
        """Start and iterate the ordered final-stage outputs."""
        self.start(items)
        try:
            while True:
                item = self._next_out()
                if isinstance(item, _EOF):
                    return
                yield item[1]
        finally:
            # an abandoned/broken consumer must not strand the workers
            self.cancel()

    def feed_source(self, items: Iterable[Any]) -> "FeedSource":
        """Adapt to DeviceFeed's `FeedSource` protocol: the feed engine
        pulls ready (chunk, n_valid) pairs straight off the pipeline's
        ordered out queue — N decode workers drive the feed without an
        extra hand-off thread."""
        return _PipelineFeedSource(self, items)


class _PipelineFeedSource(FeedSource):
    """FeedSource over a HostPipeline's ordered output (see
    io/feed.py for the protocol DeviceFeed.run consumes)."""

    def __init__(self, pipe: HostPipeline, items: Iterable[Any]):
        self._pipe = pipe
        self._items = items
        self._done = False

    def start(self):
        self._pipe.start(self._items)

    def _translate(self, block: bool):
        if self._done:
            return FEED_END
        try:
            item = self._pipe._next_out(block=block)
        except queue.Empty:
            raise
        except BaseException:  # noqa: BLE001 — surfaced via error()
            # feed.run raises source.error() after draining in-flight
            # work, so the error still propagates — without deadlocking
            # the transfer window mid-group
            self._done = True
            return FEED_END
        if isinstance(item, _EOF):
            self._done = True
            return FEED_END
        return item[1]

    def get(self):
        return self._translate(block=True)

    def get_nowait(self):
        return self._translate(block=False)

    def error(self) -> Optional[BaseException]:
        return self._pipe.error
