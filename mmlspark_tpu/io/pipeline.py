"""HostPipeline: the streaming input pipeline engine.

BENCH_r05 measured the gap this module closes: the ResNet-50 forward
sustains 11,167 images/sec while end-to-end ImageFeaturizer delivers
134.4 — the host stages (decode -> assemble -> h2d -> forward) ran
largely serially per batch, so e2e throughput was the SUM of stage
times instead of the MAX.  This is the pipelined-prefetch argument of
tf.data (Murray et al., VLDB 2021) and DALI's move-preprocessing-to-
accelerator design, applied to this stack:

  * **Stages with worker pools.**  A `HostPipeline` is an ordered list
    of `PipelineStage(name, fn, workers)` map stages.  Each stage owns
    `workers` threads pulling from a bounded input queue; the decode
    codecs (libjpeg via `native`, PIL) release the GIL, so N decode
    workers decode N chunks concurrently while later stages and the
    device run ahead on earlier ones.
  * **Bounded hand-off queues = backpressure.**  Every stage boundary
    is a bounded queue; a slow device stalls assembly, which stalls
    decode — memory stays O(queue_size x chunk), never O(dataset).
  * **Order-preserving emission.**  Workers finish out of order; a
    per-stage reorder buffer re-emits results in sequence so chunk
    results land in feed order and the DeviceFeed's coalescer still
    sees same-shape runs back to back.
  * **Feeds DeviceFeed directly.**  `feed_source(items)` adapts the
    pipeline's ordered output to the feed engine's `FeedSource`
    protocol (io/feed.py), so decode of chunk N+2, h2d of N+1, and the
    forward of N are in flight simultaneously with no extra copy or
    hand-off thread in between.
  * **Telemetry.**  Per-stage busy seconds and item counts accumulate
    in `PIPELINE_TELEMETRY` (bench.py derives `decode_ms` /
    `host_assemble_ms` and the `e2e_bound` attribution from deltas);
    each item observes `io.pipeline.stage.latency{stage=...}`, queue
    depths mirror to `io.pipeline.queue.depth.<stage>` gauges, and when
    the submitting thread is inside a trace every stage item records a
    `pipeline.<stage>` child span — `/trace/<id>` shows decode spans of
    later batches overlapping the transfer/forward of earlier ones.

Failure semantics: a stage exception (or a producer exception) cancels
the pipeline, and the consumer re-raises the ORIGINAL error — no
deadlock, no silent truncation.  All queue waits are cancel-aware
timeout loops, so an abandoned consumer (generator closed early) or a
dead consumer can never strand a worker.  See docs/performance.md
("The input pipeline").
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from ..core import telemetry as core_telemetry
from .feed import FEED_END, FeedSource

__all__ = ["PipelineStage", "HostPipeline", "PipelineTelemetry",
           "PIPELINE_TELEMETRY", "pipeline_workers"]

_POLL_S = 0.05  # cancel-aware queue wait quantum


def pipeline_workers(default: Optional[int] = None) -> int:
    """Decode/assembly worker count: MMLSPARK_PIPELINE_WORKERS overrides
    (the knob every adopter inherits); otherwise `default`, otherwise a
    conservative min(4, cores) — decode threads beyond the core count
    only add queue contention."""
    env = os.environ.get("MMLSPARK_PIPELINE_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if default is not None:
        return max(1, int(default))
    return max(1, min(4, os.cpu_count() or 2))


class PipelineTelemetry:
    """Thread-safe per-stage busy-seconds / item counters.

    `busy_s` for a stage is the sum of wall time its workers spend
    inside the stage fn — items/busy_s is the stage's standalone
    throughput bound, which is exactly what `e2e_bound` attribution
    needs (the pipeline's steady-state rate is min over stages of
    items/busy_s x workers)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._stages: Dict[str, Dict[str, float]] = {}  #: guarded-by self._lock

    def add(self, stage: str, busy_s: float = 0.0, items: int = 0):
        with self._lock:
            rec = self._stages.setdefault(stage,
                                          {"busy_s": 0.0, "items": 0.0})
            rec["busy_s"] += busy_s
            rec["items"] += items

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stages.items()}

    def delta(self, since: Dict[str, Dict[str, float]]
              ) -> Dict[str, Dict[str, float]]:
        now = self.snapshot()
        out = {}
        for k, v in now.items():
            base = since.get(k, {})
            out[k] = {f: v[f] - base.get(f, 0.0) for f in v}
        return out


# process-wide default sink: bench.py and tests read deltas off this
PIPELINE_TELEMETRY = PipelineTelemetry()


class PipelineStage:
    """One map stage: `fn(value) -> value`, run by `workers` threads.

    `fn` must be thread-safe for workers > 1 (the decode/assembly fns
    here close over read-only inputs and write disjoint outputs)."""

    def __init__(self, name: str, fn: Callable[[Any], Any],
                 workers: int = 1):
        self.name = str(name)
        self.fn = fn
        self.workers = max(1, int(workers))


class _EOF:
    """End-of-stream marker carrying the total item count; re-put by the
    worker that pops it so every sibling sees it, forwarded downstream
    by the reorder buffer only after all `total` items emitted."""

    __slots__ = ("total",)

    def __init__(self, total: int):
        self.total = total


class _Reorder:
    """Order-restoring emitter between a stage's workers and the next
    queue: out-of-order completions park in `pending` until their turn.
    `put` may block on a full downstream queue while the lock is held —
    that IS the backpressure (siblings stall on the lock instead of
    racing further ahead); the consumer side never takes this lock, so
    there is no cycle to deadlock on."""

    def __init__(self, put: Callable[[Any], None]):
        self._put = put
        self._lock = threading.Lock()
        self._pending: Dict[int, Any] = {}  #: guarded-by self._lock
        self._next = 0  #: guarded-by self._lock
        self._total: Optional[int] = None  #: guarded-by self._lock
        self._eof_sent = False  #: guarded-by self._lock

    def emit(self, seq: int, value: Any):
        with self._lock:
            self._pending[seq] = value
            self._flush()

    def close(self, total: int):
        with self._lock:
            self._total = total
            self._flush()

    def _flush(self):
        while self._next in self._pending:
            self._put((self._next, self._pending.pop(self._next)))
            self._next += 1
        if (self._total is not None and self._next >= self._total
                and not self._eof_sent):
            self._eof_sent = True
            self._put(_EOF(self._total))


class HostPipeline:
    """Bounded multi-stage streaming pipeline over an item iterable.

    Drive it one of three ways:
      * `run(items)` — iterate the ordered final-stage outputs;
      * `feed_source(items)` — a `FeedSource` for `DeviceFeed.run`
        (the chunk path: stage outputs must be (chunk, n_valid) pairs);
      * `start(items)` + manual draining (tests).

    One pipeline instance is single-use (queues and counters are per
    run); instances are cheap — threads spawn at `start`."""

    def __init__(self, stages: Sequence[PipelineStage],
                 queue_size: Optional[int] = None,
                 telemetry: Optional[PipelineTelemetry] = None):
        if not stages:
            raise ValueError("HostPipeline needs at least one stage")
        self.stages = list(stages)
        # deep enough that every worker of the widest stage can have one
        # item in hand and one queued; small enough to bound host memory
        self.queue_size = max(2, int(
            queue_size if queue_size is not None
            else 2 * max(s.workers for s in self.stages)))
        self.telemetry = (telemetry if telemetry is not None
                          else PIPELINE_TELEMETRY)
        self._queues: List["queue.Queue"] = []
        self._qnames: List[str] = []
        self._cancelled = threading.Event()
        self._err_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        # every stage worker and the producer race through _q_put; the
        # read-modify-write max-merge below needs its own (tiny) lock
        self._hw_lock = threading.Lock()
        self._high_water: Dict[str, int] = {}  #: guarded-by self._hw_lock
        self._started = False
        self._ctx = None  # (trace_id, span_id) captured at start

    # ---- lifecycle -----------------------------------------------------
    def start(self, items: Iterable[Any]):
        """Spawn the producer and every stage's workers (all daemon)."""
        if self._started:
            raise RuntimeError("HostPipeline instances are single-use")
        self._started = True
        # spans from worker threads attach to the trace active where the
        # pipeline was STARTED (the transform/fit caller), the same
        # cross-thread hop record_span exists for
        self._ctx = core_telemetry.current_context()
        self._queues = [queue.Queue(maxsize=self.queue_size)
                        for _ in self.stages]
        self._queues.append(queue.Queue(maxsize=self.queue_size))  # out
        self._qnames = [s.name for s in self.stages] + ["out"]
        threading.Thread(target=self._produce, args=(items,), daemon=True,
                         name="host-pipeline-producer").start()
        for i, stage in enumerate(self.stages):
            reorder = _Reorder(
                lambda item, j=i + 1: self._q_put(j, item))
            for w in range(stage.workers):
                threading.Thread(
                    target=self._worker, args=(stage, i, reorder),
                    daemon=True,
                    name=f"host-pipeline-{stage.name}-{w}").start()

    def cancel(self):
        """Stop all workers promptly; safe to call repeatedly."""
        self._cancelled.set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def high_water(self) -> Dict[str, int]:
        """Max observed depth per hand-off queue (keyed by the stage the
        queue feeds, plus 'out') — the structural overlap witness: a
        stage queue that reached depth >= 2 had the previous stage
        running ahead while this one was still busy."""
        with self._hw_lock:
            return dict(self._high_water)

    def _note_depth(self, name: str, depth: int) -> None:
        """Max-merge one depth observation; lost updates here would
        under-report overlap and silently pass the structural check."""
        with self._hw_lock:
            if depth > self._high_water.get(name, 0):
                self._high_water[name] = depth

    # ---- queue plumbing ------------------------------------------------
    def _q_put(self, idx: int, item: Any):
        q = self._queues[idx]
        name = self._qnames[idx]
        while not self._cancelled.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                break
            except queue.Full:
                continue
        depth = q.qsize()
        self._note_depth(name, depth)
        core_telemetry.gauge(f"io.pipeline.queue.depth.{name}").set(depth)

    def _fail(self, e: BaseException):
        with self._err_lock:
            if self._error is None:
                self._error = e
        self.cancel()

    def _produce(self, items: Iterable[Any]):
        n = 0
        try:
            for item in items:
                self._q_put(0, (n, item))
                n += 1
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            self._fail(e)
            return
        self._q_put(0, _EOF(n))

    def _worker(self, stage: PipelineStage, idx: int, reorder: _Reorder):
        in_q = self._queues[idx]
        while not self._cancelled.is_set():
            try:
                item = in_q.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            if isinstance(item, _EOF):
                # sibling workers need the marker too
                self._q_put(idx, item)
                reorder.close(item.total)
                return
            seq, value = item
            t0 = time.perf_counter()
            try:
                # profiler annotation only when armed via
                # enable_device_annotations() — same name as the
                # record_span below so timelines and traces line up
                with core_telemetry.device_annotation(
                        f"pipeline.{stage.name}"):
                    out = stage.fn(value)
            except BaseException as e:  # noqa: BLE001 — forwarded
                self._fail(e)
                return
            dt = time.perf_counter() - t0
            self.telemetry.add(stage.name, busy_s=dt, items=1)
            core_telemetry.histogram("io.pipeline.stage.latency",
                                     stage=stage.name).observe(dt)
            core_telemetry.incr(f"io.pipeline.items.{stage.name}")
            if self._ctx is not None:
                core_telemetry.record_span(f"pipeline.{stage.name}",
                                           self._ctx, dt, seq=seq)
            reorder.emit(seq, out)

    # ---- consumption ---------------------------------------------------
    def _next_out(self, block: bool = True):
        """Next ordered (seq, value) from the out queue; `_EOF` at clean
        end; raises the pipeline's error, or queue.Empty when
        non-blocking and nothing is ready."""
        q = self._queues[-1]
        while True:
            try:
                item = q.get(block=block, timeout=_POLL_S if block else None)
            except queue.Empty:
                if self._error is not None:
                    raise self._error
                if self._cancelled.is_set():
                    raise RuntimeError("HostPipeline cancelled")
                if block:
                    continue
                raise
            if isinstance(item, _EOF):
                if self._error is not None:
                    raise self._error
                return item
            return item

    def run(self, items: Iterable[Any]):
        """Start and iterate the ordered final-stage outputs."""
        self.start(items)
        try:
            while True:
                item = self._next_out()
                if isinstance(item, _EOF):
                    return
                yield item[1]
        finally:
            # an abandoned/broken consumer must not strand the workers
            self.cancel()

    def feed_source(self, items: Iterable[Any]) -> "FeedSource":
        """Adapt to DeviceFeed's `FeedSource` protocol: the feed engine
        pulls ready (chunk, n_valid) pairs straight off the pipeline's
        ordered out queue — N decode workers drive the feed without an
        extra hand-off thread."""
        return _PipelineFeedSource(self, items)


class _PipelineFeedSource(FeedSource):
    """FeedSource over a HostPipeline's ordered output (see
    io/feed.py for the protocol DeviceFeed.run consumes)."""

    def __init__(self, pipe: HostPipeline, items: Iterable[Any]):
        self._pipe = pipe
        self._items = items
        self._done = False

    def start(self):
        self._pipe.start(self._items)

    def _translate(self, block: bool):
        if self._done:
            return FEED_END
        try:
            item = self._pipe._next_out(block=block)
        except queue.Empty:
            raise
        except BaseException:  # noqa: BLE001 — surfaced via error()
            # feed.run raises source.error() after draining in-flight
            # work, so the error still propagates — without deadlocking
            # the transfer window mid-group
            self._done = True
            return FEED_END
        if isinstance(item, _EOF):
            self._done = True
            return FEED_END
        return item[1]

    def get(self):
        return self._translate(block=True)

    def get_nowait(self):
        return self._translate(block=False)

    def error(self) -> Optional[BaseException]:
        return self._pipe.error
