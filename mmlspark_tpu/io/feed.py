"""DeviceFeed: the unified host->device transfer engine.

Every consumer that moves bulk data onto the chip — ImageFeaturizer's
streaming byte path, TPUModel's executor feed, DeepVisionClassifier's
train loop, `fit_epochs`, and the serving ContinuousBatcher's per-tick
uploads — routes its transfers through this module.  The reference
system solved the same problem on Spark by consolidating small
partitions into large batched transfers before they hit the native
engine (MiniBatchBase/FlattenBatch + PartitionConsolidator); here the
fixed per-transfer cost of the link (dominant through the tunneled dev
chip: BENCH_r05 measured 385 img/s of h2d against an 11k img/s forward)
is amortized the same way, JAX-first:

  * **Transfer coalescing.**  Consecutive same-shape chunks pack into
    one `[k, bs, ...]` staging buffer and ride ONE `device_put`; mixed
    shape/dtype chunks byte-pack into a single uint8 wire buffer with a
    byte-offset header and are sliced/bitcast back apart ON DEVICE.
    Coalescing is adaptive: the engine drains whatever the producer has
    ready and never waits for a fuller pack (`greedy=True`), so a
    decode-bound pipeline degrades to singleton transfers with zero
    added latency while a compute/transfer-bound one packs to the cap.
  * **uint8 wire format.**  The engine is dtype-preserving: image paths
    feed uint8 end-to-end (4x fewer bytes than f32) and the consumer's
    jitted program does the cast/normalize on device (ImagePreprocess).
  * **Ring of staging buffers, bounded depth.**  Host packing buffers
    come from a per-wire-shape ring of `depth + 1` slots reused round
    robin — no per-batch allocation; a slot is rewritten only after the
    group that used it has fully drained (device_put can alias host
    memory zero-copy on the CPU backend, so reuse MUST be fenced on the
    consumer side).  The packed device buffer is donated to the unpack
    program, so its HBM is released/aliased the moment the chunks are
    split apart.  `depth` packed transfers are in flight at once
    (default 2, tunable — e.g. 4 for very high-latency links).
  * **Telemetry.**  Bytes moved, transfer calls/seconds, per-stage
    stall seconds, and wall time accumulate in `FEED_TELEMETRY`;
    `bench.py` folds the derived `overlap_frac`/`stall_s`/`h2d_gbps`
    into its JSON line.  See docs/performance.md ("The h2d feed").
  * **Fault tolerance.**  Every `device_put` sits behind the
    `feed.device_put` fault point with a bounded retry
    (`transfer_retries`, tiny backoff — a transient link hiccup costs
    microseconds, not a failed batch).  Since the graftflow unification
    the retry ladder is a `core.flow.StagePolicy` (the same
    retry-then-degrade shape every flow stage can wear), with backoff
    sleeps through the injectable clock.  A PACKED transfer that fails
    all its retries **degrades the engine**: the group falls back to
    plain per-chunk puts and the instance stays on the safe unpipelined
    path (no coalescing, no in-flight window) for the rest of its life —
    correctness first, the packed fast path is an optimization.  Retries
    and degradations count into `core.telemetry` ("feed.transfer_retry",
    "feed.degraded"); see docs/robustness.md (degradation ladder).
  * **A registered flow stage.**  `DeviceFeed.stage()` exposes the h2d
    hop as an `H2DStage` for credit-bounded FlowGraphs
    (decode -> assemble -> h2d), with the `flow.h2d` fault point and
    declared `flow.*.h2d` telemetry (lint rule G405).
"""
from __future__ import annotations

import os
import queue
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..core import telemetry as core_telemetry
from ..core.flow import Stage, StagePolicy
from ..utils.faults import fault_point
from ..utils.sync import make_lock

__all__ = ["DeviceFeed", "H2DStage", "FeedTelemetry", "FEED_TELEMETRY",
           "default_depth", "FeedSource", "FEED_END"]

_ALIGN = 128  # byte-pack offset alignment (covers every feed dtype's itemsize)

# terminal marker a FeedSource returns once its stream is exhausted
FEED_END = object()


class FeedSource:
    """Protocol for multi-producer chunk sources driving `DeviceFeed.run`.

    PR 2's `run()` hid exactly one prefetch thread behind a plain
    iterator — decode AND assembly serialized on it.  A FeedSource owns
    its production concurrency (the HostPipeline adapter in
    io/pipeline.py runs N decode workers) and the feed engine just pulls
    ready chunks:

      * ``start()``    — begin producing (called once by `run`).
      * ``get()``      — block until the next (chunk, n_valid) item, or
                         return ``FEED_END`` when the stream is done
                         (terminal: keep returning it).
      * ``get_nowait()``— same, but raise ``queue.Empty`` instead of
                         blocking when nothing is ready yet.
      * ``error()``    — the producer-side exception to re-raise after
                         in-flight groups drain, or None.

    Plain iterables passed to `run()` are wrapped in `_IterSource`,
    which reproduces the old single-prefetch-thread behavior exactly —
    the original signature keeps working."""

    def start(self) -> None:
        raise NotImplementedError

    def get(self):
        raise NotImplementedError

    def get_nowait(self):
        raise NotImplementedError

    def error(self) -> Optional[BaseException]:
        return None


class _IterSource(FeedSource):
    """The PR-2 shape: one daemon thread drains `chunk_iter` into a
    bounded queue (decode/assembly overlap device compute; backpressure
    via maxsize)."""

    def __init__(self, chunk_iter: Iterable, maxsize: int):
        self._it = chunk_iter
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._err: List[BaseException] = []

    def start(self):
        threading.Thread(target=self._produce, daemon=True,
                         name="device-feed-producer").start()

    def _produce(self):
        try:
            for item in self._it:
                self._q.put(item)
                core_telemetry.gauge("io.feed.queue.depth").set(
                    self._q.qsize())
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            self._err.append(e)
        finally:
            self._q.put(FEED_END)

    def _terminal(self, item):
        if item is FEED_END:
            self._q.put(FEED_END)  # stay terminal for later gets
        return item

    def get(self):
        return self._terminal(self._q.get())

    def get_nowait(self):
        return self._terminal(self._q.get_nowait())

    def error(self) -> Optional[BaseException]:
        return self._err[0] if self._err else None


def default_depth() -> int:
    """Pipeline depth: packed transfers in flight (MMLSPARK_FEED_DEPTH
    overrides for experiments; the knob every consumer inherits)."""
    try:
        return max(1, int(os.environ.get("MMLSPARK_FEED_DEPTH", "2")))
    except ValueError:
        return 2


class FeedTelemetry:
    """Thread-safe monotonic counters for the feed engine.

    `transfer_s` is the wall time the feeding thread spends inside
    `device_put` dispatch — through a synchronous transport (the
    tunneled chip, the CPU backend) that IS the host-visible transfer
    cost; a fully async transport under-reports, which only makes the
    derived `overlap_frac` conservative in the other direction (it can
    report transfers as hidden when they were simply invisible).
    """

    _FIELDS = ("bytes_moved", "transfer_calls", "transfer_s", "chunks_fed",
               "coalesced_chunks", "groups", "stall_decode_s",
               "stall_drain_s", "compute_s", "wall_s")

    def __init__(self):
        self._lock = make_lock("io.feed.telemetry")
        self._c: Dict[str, float] = {f: 0.0 for f in self._FIELDS}

    def add(self, **kw: float):
        with self._lock:
            for k, v in kw.items():
                self._c[k] += v

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._c)

    def delta(self, since: Dict[str, float]) -> Dict[str, float]:
        now = self.snapshot()
        return {k: now[k] - since.get(k, 0.0) for k in now}

    @staticmethod
    def summarize(d: Dict[str, float]) -> Dict[str, Any]:
        """Derived metrics from a counter delta — the bench.py fields.

        overlap_frac: fraction of feed wall time NOT spent blocked on
        host-side feeding (decode stalls + transfer dispatch).  1.0
        means every transfer hid under device compute; through a
        bandwidth-bound tunnel it collapses toward 0.
        """
        wall = d.get("wall_s", 0.0)
        stall = d.get("stall_decode_s", 0.0) + d.get("stall_drain_s", 0.0)
        blocked = d.get("stall_decode_s", 0.0) + d.get("transfer_s", 0.0)
        out = {
            "feed_bytes": int(d.get("bytes_moved", 0)),
            "transfer_calls": int(d.get("transfer_calls", 0)),
            "chunks_fed": int(d.get("chunks_fed", 0)),
            "stall_s": round(stall, 4),
            "overlap_frac": (round(max(0.0, min(1.0, 1.0 - blocked / wall)), 4)
                             if wall > 0 else None),
            "h2d_gbps": (round(d["bytes_moved"] / d["transfer_s"] / 1e9, 4)
                         if d.get("transfer_s", 0) > 0 else None),
        }
        # mirror the derived numbers onto the registry so /metrics and
        # export_snapshot() carry the latest feed summary
        core_telemetry.gauge("io.feed.stall_s").set(out["stall_s"])
        if out["overlap_frac"] is not None:
            core_telemetry.gauge("io.feed.overlap_frac").set(
                out["overlap_frac"])
        return out


# process-wide default sink: bench.py and tests read deltas off this
FEED_TELEMETRY = FeedTelemetry()


def _first_call(fn, arg):
    """First (compiling) invocation of an unpack program: the donated
    staging buffer's split outputs are smaller than the input, so XLA can
    never alias them and warns — the donation is still wanted (it frees
    the packed HBM at execution instead of at Python ref-drop), so the
    expected warning is silenced for exactly this call."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        return fn(arg)


class _RingSlot:
    __slots__ = ("buf", "busy", "fence")

    def __init__(self):
        self.buf: Optional[np.ndarray] = None
        self.busy = False
        self.fence: Any = None  # device values to block on before reuse


class DeviceFeed:
    """One host->device feed: coalescing + ring staging + depth pipelining.

    mesh=None feeds the default device uncommitted (the serving shape);
    with a mesh, `run()` feeds batch-sharded chunks over the 'data' axis.
    Instances are cheap (rings allocate lazily); consumers create one per
    transform/fit/loop and share the process-wide telemetry sink.
    """

    def __init__(self, mesh=None, depth: Optional[int] = None,
                 coalesce: int = 4, coalesce_bytes: int = 64 << 20,
                 telemetry: Optional[FeedTelemetry] = None,
                 transfer_retries: int = 3):
        self.mesh = mesh
        self.depth = max(1, int(depth if depth is not None else default_depth()))
        self.coalesce = max(1, int(coalesce))
        self.coalesce_bytes = int(coalesce_bytes)
        self.telemetry = telemetry if telemetry is not None else FEED_TELEMETRY
        self.transfer_retries = max(1, int(transfer_retries))
        # the retry rungs of the degradation ladder, as the shared
        # StagePolicy shape (core/flow.py); the terminal degrade rung
        # stays at the call sites, which know whether the failed put was
        # packed (degrade the engine) or already a singleton (raise)
        self._put_policy = StagePolicy(retries=self.transfer_retries,
                                       backoff_s=0.001, backoff_cap_s=0.05,
                                       retry_counter="feed.transfer_retry")
        # a packed transfer that failed all its retries flips this: the
        # instance stays on the safe per-chunk unpipelined path for the
        # rest of its life (instances are per-transform/fit, so the blast
        # radius of a flaky link is one consumer, not the process)
        self.degraded = False
        self._rings: Dict[Any, List[_RingSlot]] = {}
        self._ring_pos: Dict[Any, int] = {}
        self._unpackers: Dict[Any, Callable] = {}
        # materialize the degraded-engines gauge at 0 so a /metrics scrape
        # sees the series before (and whether or not) anything degrades
        core_telemetry.gauge("io.feed.degraded_engines")

    def _obs_transfer(self, nbytes: float, dt: float, chunks: int) -> None:
        """Per-transfer registry instrumentation: latency + size
        histograms always; a `feed.transfer` child span when the calling
        thread is inside a trace (a served request's batch tick), so the
        device upload shows up in that request's `/trace/<id>` tree."""
        core_telemetry.histogram("io.feed.transfer.latency").observe(dt)
        core_telemetry.histogram(
            "io.feed.transfer.bytes",
            boundaries=core_telemetry.BYTE_BUCKETS).observe(nbytes)
        ctx = core_telemetry.current_context()
        if ctx is not None:
            core_telemetry.record_span("feed.transfer", ctx, dt,
                                       bytes=int(nbytes), chunks=chunks)

    # ---- guarded transfer ----------------------------------------------
    def _device_put(self, arr, sharding=None):
        """The one raw `jax.device_put` in the engine: named fault point +
        bounded retry with a tiny backoff (a transient link error costs
        microseconds, not the batch), run as a `StagePolicy` ladder."""
        import jax

        def attempt(a):
            fault_point("feed.device_put")
            # no-op unless enable_device_annotations() armed the
            # profiler hook: the transfer span itself is recorded
            # after the fact via record_span, which can't annotate
            with core_telemetry.device_annotation("feed.transfer"):
                return (jax.device_put(a, sharding)
                        if sharding is not None
                        else jax.device_put(a))

        return self._put_policy.run(attempt, arr)

    def _degrade(self, why: str):
        if not self.degraded:
            self.degraded = True
            core_telemetry.incr("feed.degraded")
            core_telemetry.gauge("io.feed.degraded_engines").inc()
            warnings.warn(f"DeviceFeed degraded to unpipelined transfers: {why}",
                          RuntimeWarning, stacklevel=3)

    # ---- sharding helpers ----------------------------------------------
    def _dp(self) -> int:
        return self.mesh.shape["data"] if self.mesh is not None else 1

    def _chunk_sharding(self, ndim: int):
        if self.mesh is None:
            return None
        from ..parallel.mesh import batch_sharding

        return batch_sharding(self.mesh, ndim)

    def _packed_sharding(self, ndim: int):
        """Sharding for a [k, bs, ...] packed buffer: batch axis is dim 1."""
        if self.mesh is None:
            return None
        from ..parallel.mesh import batch_sharding

        return batch_sharding(self.mesh, ndim, batch_axis=1)

    # ---- single transfers ----------------------------------------------
    def put(self, arr, sharding=None, block: bool = False):
        """One counted `device_put`.  `block=True` waits for the transfer
        (bandwidth probes); otherwise dispatch is async like raw jax."""
        import jax

        arr = np.asarray(arr)
        t0 = time.perf_counter()
        out = self._device_put(arr, sharding)
        if block:
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self.telemetry.add(bytes_moved=arr.nbytes, transfer_calls=1,
                           transfer_s=dt, chunks_fed=1, groups=1)
        self._obs_transfer(arr.nbytes, dt, 1)
        return out

    def put_group(self, arrays: Sequence[np.ndarray], shardings=None,
                  sharded_multi: bool = False):
        """Several host arrays -> device in ONE transfer when profitable.

        Arrays byte-pack into a single uint8 wire buffer (offset header)
        and are sliced/bitcast apart on device — one fixed per-transfer
        cost instead of len(arrays).  On a multi-device mesh a replicated
        byte buffer would multiply wire bytes, so unless the caller opts
        in (`sharded_multi` for replicated consumers), packing engages
        only single-device and the call degrades to per-array puts.
        """
        import jax

        arrays = [np.ascontiguousarray(a) for a in arrays]
        if shardings is None:
            shardings = [None] * len(arrays)
        if len(arrays) == 1:
            return (self.put(arrays[0], shardings[0]),)
        multi = jax.device_count() > 1
        if multi and not sharded_multi and any(s is not None for s in shardings):
            return tuple(self.put(a, s) for a, s in zip(arrays, shardings))
        if self.degraded:
            return tuple(self.put(a, s) for a, s in zip(arrays, shardings))

        layout = []
        off = 0
        for a in arrays:
            layout.append((off, a.shape, a.dtype.str))
            off += -(-a.nbytes // _ALIGN) * _ALIGN
        total = max(off, _ALIGN)
        slot = self._acquire_slot(("bytes", total), (total,), np.uint8)
        for a, (o, _s, _d) in zip(arrays, layout):
            slot.buf[o:o + a.nbytes] = a.reshape(-1).view(np.uint8)
        t0 = time.perf_counter()
        try:
            packed = self._device_put(slot.buf)
        except Exception as e:  # noqa: BLE001 — degrade, then the safe path
            self._degrade(f"packed put_group failed after retries: {e}")
            return tuple(self.put(a, s) for a, s in zip(arrays, shardings))
        dt = time.perf_counter() - t0
        self.telemetry.add(bytes_moved=total, transfer_calls=1,
                           transfer_s=dt,
                           chunks_fed=len(arrays), groups=1,
                           coalesced_chunks=len(arrays))
        self._obs_transfer(total, dt, len(arrays))
        outs = self._unpack_bytes(packed, tuple(layout), shardings)
        # the slot is rewritten only after these outputs exist on device
        slot.fence = outs
        return outs

    def stream(self, items: Iterable[Tuple[np.ndarray, ...]], shardings=None,
               sharded_multi: bool = False):
        """Prefetching transfer stream for sequential consumers (train
        loops): yields each item's device arrays while keeping up to
        `depth` later items' transfers already dispatched — slice t+1
        moves while the scanned epoch for slice t computes.  Each item
        (a tuple of host arrays) rides one packed transfer when the mesh
        is single-device (`put_group`)."""
        buf: deque = deque()
        t0 = time.perf_counter()
        for item in items:
            buf.append(self.put_group(tuple(item), shardings,
                                      sharded_multi=sharded_multi))
            while len(buf) > self.depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
        self.telemetry.add(wall_s=time.perf_counter() - t0)

    # ---- the pipelined chunk engine ------------------------------------
    def run(self, chunk_iter, compute_fn: Callable,
            greedy: bool = True) -> List[np.ndarray]:
        """Drive (chunk, n_valid) pairs through transfer + compute with
        decode/transfer/compute overlap; returns per-chunk host outputs
        trimmed to n_valid, in feed order.

        `chunk_iter` is either a plain iterable — it runs on ONE
        prefetch thread (`_IterSource`; decode/assembly overlap device
        compute) — or a `FeedSource` that owns its own production
        concurrency (HostPipeline's N decode workers feed the same
        consumer loop; io/pipeline.py).  Ready chunks coalesce into
        packed groups (same shape/dtype: one [k, bs, ...] buffer; mixed
        on a single device: one byte-packed buffer); each group is ONE
        `device_put`, split apart on device by a donated unpack program,
        and `compute_fn` is dispatched per chunk.  Up to `depth` groups
        are in flight; the oldest drains (async-fetched) when the window
        fills.

        greedy=True never waits for a fuller pack (latency-first; the
        transform path).  greedy=False waits until `coalesce` chunks are
        queued (or the producer is done) before forming each group —
        maximum amortization when total latency is what matters (bulk
        jobs, the microbench)."""
        import jax

        tel = self.telemetry
        t_wall = time.perf_counter()
        if isinstance(chunk_iter, FeedSource):
            source = chunk_iter
        else:
            source = _IterSource(chunk_iter,
                                 maxsize=max(4 * self.coalesce,
                                             2 * self.depth))
        source.start()

        results: List[np.ndarray] = []
        inflight: deque = deque()  # (ys, ns, slot) per group, feed order
        done = False
        leftover: Optional[Tuple[np.ndarray, int]] = None

        def drain_group():
            ys, ns, slot = inflight.popleft()
            t0 = time.perf_counter()
            for y, n in zip(ys, ns):
                results.append(np.asarray(y)[:n])
            tel.add(stall_drain_s=time.perf_counter() - t0)
            if slot is not None:
                slot.busy = False

        while not done or leftover is not None:
            # ---- collect the next group of ready chunks ----
            # a degraded engine forms singleton groups and keeps nothing
            # in flight (the safe unpipelined ladder rung; may flip
            # mid-run when a packed transfer exhausts its retries)
            coalesce_now = 1 if self.degraded else self.coalesce
            group: List[Tuple[np.ndarray, int]] = []
            gbytes = 0
            if leftover is not None:
                group.append(leftover)
                gbytes = leftover[0].nbytes
                leftover = None
            while len(group) < coalesce_now and gbytes < self.coalesce_bytes:
                if not group or (not greedy and not done):
                    t0 = time.perf_counter()
                    item = source.get()
                    tel.add(stall_decode_s=time.perf_counter() - t0)
                else:
                    try:
                        item = source.get_nowait()
                    except queue.Empty:
                        break
                if item is FEED_END:
                    done = True
                    break
                chunk, n = item
                if group and not self._can_pack(group[0][0], chunk):
                    leftover = (chunk, n)
                    break
                group.append((chunk, n))
                gbytes += chunk.nbytes
            if not group:
                continue

            # ---- one transfer for the whole group ----
            xs, slot = self._transfer_group(group)
            t0 = time.perf_counter()
            ys = []
            for x in xs:
                ys.append(compute_fn(x))
            for y in ys:
                try:
                    # start device->host DMA at dispatch so the fetch
                    # overlaps later groups instead of serializing at drain
                    y.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass
            # dispatch time; the blocked remainder of device compute
            # lands in stall_drain_s — the sum is the forward's
            # host-visible cost (bench.py's forward_ms)
            tel.add(compute_s=time.perf_counter() - t0)
            inflight.append((ys, [n for _c, n in group], slot))
            while len(inflight) > (0 if self.degraded else self.depth):
                drain_group()
        while inflight:
            drain_group()
        tel.add(wall_s=time.perf_counter() - t_wall)
        src_err = source.error()
        if src_err is not None:
            raise src_err
        return results

    # ---- packing internals ---------------------------------------------
    def _can_pack(self, a: np.ndarray, b: np.ndarray) -> bool:
        """Chunks pack together when same shape+dtype (array pack) or, on
        a single device, any shapes via the byte-packed wire (a sharded
        byte buffer cannot carry mixed batch axes across shards)."""
        if a.shape == b.shape and a.dtype == b.dtype:
            return True
        return self._dp() == 1 and (self.mesh is None
                                    or self.mesh.devices.size == 1)

    def _acquire_slot(self, key, shape, dtype) -> _RingSlot:
        """Ring slot for a packing buffer: `depth + 1` slots per wire
        shape, reused round-robin.  device_put may alias host memory
        zero-copy (CPU backend), so a busy slot must drain first and a
        fenced slot blocks on its unpacked outputs before rewrite."""
        import jax

        ring = self._rings.setdefault(key, [])
        if not ring:
            ring.extend(_RingSlot() for _ in range(self.depth + 1))
        pos = self._ring_pos.get(key, 0)
        self._ring_pos[key] = (pos + 1) % len(ring)
        slot = ring[pos]
        if slot.fence is not None:
            t0 = time.perf_counter()
            jax.block_until_ready(slot.fence)
            self.telemetry.add(stall_drain_s=time.perf_counter() - t0)
            slot.fence = None
        if slot.buf is None or slot.buf.shape != tuple(shape) \
                or slot.buf.dtype != dtype:
            slot.buf = np.empty(shape, dtype)
        return slot

    def _transfer_group(self, group):
        """ONE device_put for the group; returns (device chunks, ring slot
        or None).  Singletons skip packing entirely (no host copy).  A
        packed transfer that fails all its retries degrades the engine and
        the group falls back to per-chunk singleton transfers."""
        tel = self.telemetry

        def put_one(c):
            sh = self._chunk_sharding(c.ndim)
            t0 = time.perf_counter()
            x = self._device_put(c, sh)
            dt = time.perf_counter() - t0
            tel.add(bytes_moved=c.nbytes, transfer_calls=1,
                    transfer_s=dt, chunks_fed=1, groups=1)
            self._obs_transfer(c.nbytes, dt, 1)
            return x

        chunks = [c for c, _n in group]
        k = len(chunks)
        if k == 1 or self.degraded:
            return [put_one(c) for c in chunks], None

        first = chunks[0]
        homogeneous = all(c.shape == first.shape and c.dtype == first.dtype
                          for c in chunks)
        if homogeneous:
            key = ("pack", k, first.shape, first.dtype.str)
            slot = self._acquire_slot(key, (k,) + first.shape, first.dtype)
            # a slot stays busy until its group drains; _acquire_slot only
            # hands out free slots because the ring has depth+1 entries
            # and the in-flight window is depth
            slot.busy = True
            for i, c in enumerate(chunks):
                slot.buf[i] = c
            t0 = time.perf_counter()
            sh = self._packed_sharding(slot.buf.ndim)
            try:
                packed = self._device_put(slot.buf, sh)
            except Exception as e:  # noqa: BLE001 — degrade, then safe path
                slot.busy = False
                self._degrade(f"packed stack transfer failed after retries: {e}")
                return [put_one(c) for c in chunks], None
            dt = time.perf_counter() - t0
            tel.add(bytes_moved=slot.buf.nbytes, transfer_calls=1,
                    transfer_s=dt, chunks_fed=k, groups=1,
                    coalesced_chunks=k)
            self._obs_transfer(slot.buf.nbytes, dt, k)
            xs = list(self._unpack_stack(packed, k, first.shape,
                                         first.dtype.str))
            return xs, slot

        # mixed shapes/dtypes: byte-pack with an offset header (single
        # device only — _can_pack gates this path)
        layout = []
        off = 0
        for c in chunks:
            layout.append((off, c.shape, c.dtype.str))
            off += -(-c.nbytes // _ALIGN) * _ALIGN
        total = off
        slot = self._acquire_slot(("bytes", total), (total,), np.uint8)
        slot.busy = True
        for c, (o, _s, _d) in zip(chunks, layout):
            slot.buf[o:o + c.nbytes] = c.reshape(-1).view(np.uint8)
        t0 = time.perf_counter()
        try:
            packed = self._device_put(slot.buf)
        except Exception as e:  # noqa: BLE001 — degrade, then safe path
            slot.busy = False
            self._degrade(f"packed byte transfer failed after retries: {e}")
            return [put_one(c) for c in chunks], None
        dt = time.perf_counter() - t0
        tel.add(bytes_moved=total, transfer_calls=1,
                transfer_s=dt, chunks_fed=k, groups=1, coalesced_chunks=k)
        self._obs_transfer(total, dt, k)
        xs = list(self._unpack_bytes(packed, tuple(layout), None))
        return xs, slot

    def _unpack_stack(self, packed, k: int, shape, dtype_str: str):
        """Split a [k, bs, ...] packed buffer into k chunks on device —
        one jitted program per (k, shape) signature, input DONATED so the
        staging HBM is released/aliased at the split."""
        import jax

        key = ("stack", k, tuple(shape), dtype_str)
        fn = self._unpackers.get(key)
        if fn is None:
            out_sh = self._chunk_sharding(len(shape))

            def split(p):
                return tuple(p[i] for i in range(k))

            kw = {"donate_argnums": (0,)}
            if out_sh is not None:
                kw["out_shardings"] = (out_sh,) * k
            fn = jax.jit(split, **kw)
            self._unpackers[key] = fn
            return _first_call(fn, packed)
        return fn(packed)

    def _unpack_bytes(self, packed, layout, shardings):
        """Slice + bitcast + reshape the byte-packed wire buffer back into
        its arrays on device — one jitted program per layout signature
        (offsets are static; serving's per-tick layout is constant, so
        this compiles once)."""
        import jax

        key = ("bytes", layout, tuple(str(s) for s in shardings or ()))
        fn = self._unpackers.get(key)
        if fn is None:
            def unpack(buf):
                outs = []
                for off, shape, dstr in layout:
                    dt = np.dtype(dstr)
                    n = int(np.prod(shape, dtype=np.int64))
                    seg = buf[off:off + n * dt.itemsize]
                    if dt == np.uint8:
                        arr = seg
                    else:
                        arr = jax.lax.bitcast_convert_type(
                            seg.reshape(n, dt.itemsize), dt)
                    outs.append(arr.reshape(shape))
                return tuple(outs)

            kw: Dict[str, Any] = {"donate_argnums": (0,)}
            if shardings is not None and any(s is not None for s in shardings):
                kw["out_shardings"] = tuple(shardings)
            fn = jax.jit(unpack, **kw)
            self._unpackers[key] = fn
            return _first_call(fn, packed)
        return fn(packed)

    # ---- the flow adapter ----------------------------------------------
    def stage(self, workers: int = 1,
              credits: Optional[int] = None) -> "H2DStage":
        """This feed's h2d hop as a graftflow `Stage`, for credit-bounded
        decode -> assemble -> h2d graphs (core/flow.py)."""
        return H2DStage(self, workers=workers, credits=credits)


class H2DStage(Stage):
    """DeviceFeed's h2d hop as a registered flow stage: each item is one
    host array (or a tuple of arrays packed into one transfer) moved
    through the feed's guarded put path — the `feed.device_put`
    StagePolicy retry ladder and the degrade-to-singletons terminal rung
    ride underneath unchanged.  The bounded credit budget is the staging
    discipline as a declared number: at most `credits` chunks staged
    host-side per graph (lint rule G405 holds every registered Stage
    subclass to one)."""

    name = "h2d"
    credits = 4

    def __init__(self, feed: Optional[DeviceFeed] = None,
                 workers: int = 1, credits: Optional[int] = None):
        super().__init__(workers=workers, credits=credits)
        self.feed = feed if feed is not None else DeviceFeed()

    def process(self, value):
        if isinstance(value, (tuple, list)):
            return self.feed.put_group(
                tuple(np.asarray(a) for a in value))
        return self.feed.put(np.asarray(value))
