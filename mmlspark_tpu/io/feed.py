"""DeviceFeed: the unified host->device transfer engine.

Every consumer that moves bulk data onto the chip — ImageFeaturizer's
streaming byte path, TPUModel's executor feed, DeepVisionClassifier's
train loop, `fit_epochs`, and the serving ContinuousBatcher's per-tick
uploads — routes its transfers through this module.  The reference
system solved the same problem on Spark by consolidating small
partitions into large batched transfers before they hit the native
engine (MiniBatchBase/FlattenBatch + PartitionConsolidator); here the
fixed per-transfer cost of the link (dominant through the tunneled dev
chip: BENCH_r05 measured 385 img/s of h2d against an 11k img/s forward)
is amortized the same way, JAX-first:

  * **Transfer coalescing.**  Consecutive same-shape chunks pack into
    one `[k, bs, ...]` staging buffer and ride ONE `device_put`; mixed
    shape/dtype chunks byte-pack into a single uint8 wire buffer with a
    byte-offset header and are sliced/bitcast back apart ON DEVICE.
    Coalescing is adaptive: the engine drains whatever the producer has
    ready and never waits for a fuller pack (`greedy=True`), so a
    decode-bound pipeline degrades to singleton transfers with zero
    added latency while a compute/transfer-bound one packs to the cap.
  * **uint8 wire format.**  The engine is dtype-preserving: image paths
    feed uint8 end-to-end (4x fewer bytes than f32) and the consumer's
    jitted program does the cast/normalize on device (ImagePreprocess).
  * **Ring of staging buffers, bounded depth.**  Host packing buffers
    come from a per-wire-shape ring of `depth + 1` slots reused round
    robin — no per-batch allocation; a slot is rewritten only after the
    group that used it has fully drained (device_put can alias host
    memory zero-copy on the CPU backend, so reuse MUST be fenced on the
    consumer side).  The packed device buffer is donated to the unpack
    program, so its HBM is released/aliased the moment the chunks are
    split apart.  `depth` packed transfers are in flight at once
    (default 2, tunable — e.g. 4 for very high-latency links).
  * **Telemetry.**  Bytes moved, transfer calls/seconds, per-stage
    stall seconds, and wall time accumulate in `FEED_TELEMETRY`;
    `bench.py` folds the derived `overlap_frac`/`stall_s`/`h2d_gbps`
    into its JSON line.  See docs/performance.md ("The h2d feed").
  * **Fault tolerance.**  Every `device_put` sits behind the
    `feed.device_put` fault point with a bounded retry
    (`transfer_retries`, tiny backoff — a transient link hiccup costs
    microseconds, not a failed batch).  Since the graftflow unification
    the retry ladder is a `core.flow.StagePolicy` (the same
    retry-then-degrade shape every flow stage can wear), with backoff
    sleeps through the injectable clock.  A PACKED transfer that fails
    all its retries **degrades the engine**: the group falls back to
    plain per-chunk puts and the instance stays on the safe unpipelined
    path (no coalescing, no in-flight window) for the rest of its life —
    correctness first, the packed fast path is an optimization.  Retries
    and degradations count into `core.telemetry` ("feed.transfer_retry",
    "feed.degraded"); see docs/robustness.md (degradation ladder).
  * **A registered flow stage.**  `DeviceFeed.stage()` exposes the h2d
    hop as an `H2DStage` for credit-bounded FlowGraphs
    (decode -> assemble -> h2d), with the `flow.h2d` fault point and
    declared `flow.*.h2d` telemetry (lint rule G405).
  * **Sharded direct-to-chip transfers.**  On a multi-device mesh a
    single monolithic `device_put` serializes the whole batch through
    one transfer stream; with `shard_strategy="auto"` (the default) the
    feed hands evenly-divisible sharded puts to `io.shard_put.
    ShardEngine` — one concurrent per-device transfer per addressable
    shard, staged through pre-pinned size-bucketed buffers, assembled
    zero-copy with `make_array_from_single_device_arrays`.  Each shard
    rides the `feed.shard_put` fault point behind its own StagePolicy
    rung; a shard group that exhausts its retries falls back to the
    coalesced single-put path and the engine stays there
    (`shard_degraded`, one rung above the PR-2 ladder).  Non-divisible
    batches fall back per call (`h2d_path="fallback"` in bench).
  * **Compressed wire.**  `put_group` accepts `ops.wire_codec.
    RLEPayload` items (still-encoded byte-RLE chunks + a cumulative
    length table): the wire carries values+ends only — 2-20x fewer
    bytes on runnable pixel data — and the chunk is re-expanded ON
    DEVICE (Pallas page-walk kernel on TPU, `jnp.repeat` everywhere
    else; transparent fallback rung).  Tune all three knobs with
    `tools/feed_tune.py`; the winner persists via MMLSPARK_FEED_TUNED.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..core import telemetry as core_telemetry
from ..core.flow import Stage, StagePolicy
from ..utils.faults import fault_point
from ..utils.sync import make_lock

__all__ = ["DeviceFeed", "H2DStage", "FeedTelemetry", "FEED_TELEMETRY",
           "default_depth", "FeedSource", "FEED_END", "FEED_FAULT_POINTS",
           "load_tuned", "host_local_feed"]

# every fault point the feed engine can cross — chaos_soak enumerates
# this alongside flow_fault_points() so its full-coverage plan can never
# go stale when a transfer path gains a new point
FEED_FAULT_POINTS = ("feed.device_put", "feed.shard_put")

_ALIGN = 128  # byte-pack offset alignment (covers every feed dtype's itemsize)

# terminal marker a FeedSource returns once its stream is exhausted
FEED_END = object()


class FeedSource:
    """Protocol for multi-producer chunk sources driving `DeviceFeed.run`.

    PR 2's `run()` hid exactly one prefetch thread behind a plain
    iterator — decode AND assembly serialized on it.  A FeedSource owns
    its production concurrency (the HostPipeline adapter in
    io/pipeline.py runs N decode workers) and the feed engine just pulls
    ready chunks:

      * ``start()``    — begin producing (called once by `run`).
      * ``get()``      — block until the next (chunk, n_valid) item, or
                         return ``FEED_END`` when the stream is done
                         (terminal: keep returning it).
      * ``get_nowait()``— same, but raise ``queue.Empty`` instead of
                         blocking when nothing is ready yet.
      * ``error()``    — the producer-side exception to re-raise after
                         in-flight groups drain, or None.

    Plain iterables passed to `run()` are wrapped in `_IterSource`,
    which reproduces the old single-prefetch-thread behavior exactly —
    the original signature keeps working."""

    def start(self) -> None:
        raise NotImplementedError

    def get(self):
        raise NotImplementedError

    def get_nowait(self):
        raise NotImplementedError

    def error(self) -> Optional[BaseException]:
        return None


class _IterSource(FeedSource):
    """The PR-2 shape: one daemon thread drains `chunk_iter` into a
    bounded queue (decode/assembly overlap device compute; backpressure
    via maxsize)."""

    def __init__(self, chunk_iter: Iterable, maxsize: int):
        self._it = chunk_iter
        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._err: List[BaseException] = []

    def start(self):
        threading.Thread(target=self._produce, daemon=True,
                         name="device-feed-producer").start()

    def _produce(self):
        try:
            for item in self._it:
                self._q.put(item)
                core_telemetry.gauge("io.feed.queue.depth").set(
                    self._q.qsize())
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            self._err.append(e)
        finally:
            self._q.put(FEED_END)

    def _terminal(self, item):
        if item is FEED_END:
            self._q.put(FEED_END)  # stay terminal for later gets
        return item

    def get(self):
        return self._terminal(self._q.get())

    def get_nowait(self):
        return self._terminal(self._q.get_nowait())

    def error(self) -> Optional[BaseException]:
        return self._err[0] if self._err else None


def default_depth() -> int:
    """Pipeline depth: packed transfers in flight (MMLSPARK_FEED_DEPTH
    overrides for experiments; the knob every consumer inherits)."""
    try:
        return max(1, int(os.environ.get("MMLSPARK_FEED_DEPTH", "2")))
    except ValueError:
        return 2


_TUNED_LOCK = make_lock("io.feed.tuned")
_TUNED_CACHE: Dict[str, Dict[str, Any]] = {}  #: guarded-by _TUNED_LOCK


def load_tuned() -> Dict[str, Any]:
    """The autotuned feed config (`tools/feed_tune.py` winner), read from
    the MMLSPARK_FEED_TUNED path once per process.  Keys: `depth`,
    `coalesce`, `strategy` — DeviceFeed consults them for any knob the
    caller left at None.  A missing/corrupt file is an empty config, not
    an error: tuning is an optimization, never a dependency."""
    path = os.environ.get("MMLSPARK_FEED_TUNED", "")
    if not path:
        return {}
    with _TUNED_LOCK:
        cfg = _TUNED_CACHE.get(path)
        if cfg is None:
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
                cfg = doc if isinstance(doc, dict) else {}
            except (OSError, ValueError):
                cfg = {}
            _TUNED_CACHE[path] = cfg
        return cfg


def host_local_feed(model: int = 1, seq: int = 1, **kwargs) -> "DeviceFeed":
    """A DeviceFeed over THIS host's addressable chips.  On a
    multi-process mesh every host feeds only the devices it can address
    (``jax.local_devices()``), each process running its own transfer
    rings and shard_put pool against its own chips — the per-host half
    of the elastic runtime (parallel/distributed.py); the sharded path
    underneath is already per-host by construction
    (`addressable_shard_layout` maps addressable shards only).
    Single-process this is exactly ``DeviceFeed(mesh=make_mesh())``."""
    import jax

    from ..parallel.mesh import make_mesh

    mesh = make_mesh(model=model, seq=seq, devices=jax.local_devices())
    return DeviceFeed(mesh=mesh, **kwargs)


class FeedTelemetry:
    """Thread-safe monotonic counters for the feed engine.

    `transfer_s` is the wall time the feeding thread spends inside
    `device_put` dispatch — through a synchronous transport (the
    tunneled chip, the CPU backend) that IS the host-visible transfer
    cost; a fully async transport under-reports, which only makes the
    derived `overlap_frac` conservative in the other direction (it can
    report transfers as hidden when they were simply invisible).
    """

    _FIELDS = ("bytes_moved", "transfer_calls", "transfer_s", "chunks_fed",
               "coalesced_chunks", "groups", "stall_decode_s",
               "stall_drain_s", "compute_s", "wall_s",
               # the sharded direct-to-chip path (io/shard_put.py)
               "sharded_groups", "fallback_groups", "shard_puts",
               "shard_bytes", "shard_wall_s", "shard_put_s",
               # the compressed wire path (ops/wire_codec.py)
               "compressed_groups", "wire_bytes_raw", "wire_bytes_sent")
    # high-water marks, not sums (note_max; delta reports the mark itself)
    _MAX_FIELDS = ("transfer_concurrency",)

    def __init__(self):
        self._lock = make_lock("io.feed.telemetry")
        self._c: Dict[str, float] = {f: 0.0 for f in self._FIELDS}
        self._c.update({f: 0.0 for f in self._MAX_FIELDS})

    def add(self, **kw: float):
        with self._lock:
            for k, v in kw.items():
                self._c[k] += v

    def note_max(self, **kw: float):
        """Raise high-water fields (`_MAX_FIELDS`) to at least `kw`."""
        with self._lock:
            for k, v in kw.items():
                if v > self._c[k]:
                    self._c[k] = v

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._c)

    def transfer_seconds(self) -> float:
        """Cumulative host-visible H2D seconds: `device_put` dispatch
        plus the sharded per-shard puts.  The goodput ledger diffs this
        around a step's `put_group` to attribute the step's `h2d`
        segment (docs/observability.md, "The goodput plane")."""
        with self._lock:
            return self._c["transfer_s"] + self._c["shard_put_s"]

    def delta(self, since: Dict[str, float]) -> Dict[str, float]:
        now = self.snapshot()
        return {k: (now[k] if k in self._MAX_FIELDS
                    else now[k] - since.get(k, 0.0)) for k in now}

    @staticmethod
    def summarize(d: Dict[str, float]) -> Dict[str, Any]:
        """Derived metrics from a counter delta — the bench.py fields.

        overlap_frac: fraction of feed wall time NOT spent blocked on
        host-side feeding (decode stalls + transfer dispatch).  1.0
        means every transfer hid under device compute; through a
        bandwidth-bound tunnel it collapses toward 0.
        """
        wall = d.get("wall_s", 0.0)
        stall = d.get("stall_decode_s", 0.0) + d.get("stall_drain_s", 0.0)
        blocked = d.get("stall_decode_s", 0.0) + d.get("transfer_s", 0.0)
        out = {
            "feed_bytes": int(d.get("bytes_moved", 0)),
            "transfer_calls": int(d.get("transfer_calls", 0)),
            "chunks_fed": int(d.get("chunks_fed", 0)),
            "stall_s": round(stall, 4),
            "overlap_frac": (round(max(0.0, min(1.0, 1.0 - blocked / wall)), 4)
                             if wall > 0 else None),
            "h2d_gbps": (round(d["bytes_moved"] / d["transfer_s"] / 1e9, 4)
                         if d.get("transfer_s", 0) > 0 else None),
        }
        # the sharded-path breakdown (ISSUE 14): which transfer path the
        # window actually took, its per-shard bandwidth, and the transfer
        # pool's concurrency high-water
        sharded = int(d.get("sharded_groups", 0))
        fallback = int(d.get("fallback_groups", 0))
        if sharded > 0 and sharded >= fallback:
            out["h2d_path"] = "sharded"
        elif fallback > 0:
            out["h2d_path"] = "fallback"
        else:
            out["h2d_path"] = "coalesced"
        out["shard_gbps"] = (
            round(d["shard_bytes"] / d["shard_wall_s"] / 1e9, 4)
            if d.get("shard_wall_s", 0) > 0 else None)
        out["transfer_concurrency"] = (
            int(d.get("transfer_concurrency", 0)) or None)
        sent = d.get("wire_bytes_sent", 0.0)
        out["wire_ratio"] = (round(d.get("wire_bytes_raw", 0.0) / sent, 3)
                             if sent > 0 else None)
        # mirror the derived numbers onto the registry so /metrics and
        # export_snapshot() carry the latest feed summary
        core_telemetry.gauge("io.feed.stall_s").set(out["stall_s"])
        if out["overlap_frac"] is not None:
            core_telemetry.gauge("io.feed.overlap_frac").set(
                out["overlap_frac"])
        if out["wire_ratio"] is not None:
            core_telemetry.gauge("io.feed.shard.wire_ratio").set(
                out["wire_ratio"])
        return out


# process-wide default sink: bench.py and tests read deltas off this
FEED_TELEMETRY = FeedTelemetry()


def _first_call(fn, arg):
    """First (compiling) invocation of an unpack program: the donated
    staging buffer's split outputs are smaller than the input, so XLA can
    never alias them and warns — the donation is still wanted (it frees
    the packed HBM at execution instead of at Python ref-drop), so the
    expected warning is silenced for exactly this call."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        return fn(arg)


class _RingSlot:
    __slots__ = ("buf", "busy", "fence")

    def __init__(self):
        self.buf: Optional[np.ndarray] = None
        self.busy = False
        self.fence: Any = None  # device values to block on before reuse


class DeviceFeed:
    """One host->device feed: coalescing + ring staging + depth pipelining.

    mesh=None feeds the default device uncommitted (the serving shape);
    with a mesh, `run()` feeds batch-sharded chunks over the 'data' axis.
    Instances are cheap (rings allocate lazily); consumers create one per
    transform/fit/loop and share the process-wide telemetry sink.
    """

    def __init__(self, mesh=None, depth: Optional[int] = None,
                 coalesce: Optional[int] = None,
                 coalesce_bytes: int = 64 << 20,
                 telemetry: Optional[FeedTelemetry] = None,
                 transfer_retries: int = 3,
                 shard_strategy: Optional[str] = None):
        tuned = load_tuned()
        self.mesh = mesh
        if depth is None:
            depth = tuned.get("depth") or default_depth()
        self.depth = max(1, int(depth))
        if coalesce is None:
            coalesce = tuned.get("coalesce") or 4
        self.coalesce = max(1, int(coalesce))
        self.coalesce_bytes = int(coalesce_bytes)
        self.telemetry = telemetry if telemetry is not None else FEED_TELEMETRY
        self.transfer_retries = max(1, int(transfer_retries))
        # sharded-path strategy: explicit arg > env > autotuned > auto.
        # "auto"/"sharded" route evenly-divisible multi-device puts
        # through ShardEngine; "coalesced" pins the PR-2 single-put path;
        # "compressed" additionally advertises the RLE wire to consumers
        # that ask (`prefers_compressed`).
        if shard_strategy is None:
            shard_strategy = (os.environ.get("MMLSPARK_FEED_SHARD")
                              or tuned.get("strategy") or "auto")
        if shard_strategy not in ("auto", "sharded", "coalesced",
                                  "compressed"):
            raise ValueError(f"unknown shard_strategy {shard_strategy!r}")
        self.shard_strategy = shard_strategy
        # a shard group that exhausted its retries flips this: the feed
        # stays on the coalesced single-put path for the rest of its
        # life (same sticky shape as `degraded`, one rung above it)
        self.shard_degraded = False
        self._shard_engine = None
        self._shard_policy = StagePolicy(retries=self.transfer_retries,
                                         backoff_s=0.001, backoff_cap_s=0.05,
                                         retry_counter="feed.shard_retry")
        # the retry rungs of the degradation ladder, as the shared
        # StagePolicy shape (core/flow.py); the terminal degrade rung
        # stays at the call sites, which know whether the failed put was
        # packed (degrade the engine) or already a singleton (raise)
        self._put_policy = StagePolicy(retries=self.transfer_retries,
                                       backoff_s=0.001, backoff_cap_s=0.05,
                                       retry_counter="feed.transfer_retry")
        # a packed transfer that failed all its retries flips this: the
        # instance stays on the safe per-chunk unpipelined path for the
        # rest of its life (instances are per-transform/fit, so the blast
        # radius of a flaky link is one consumer, not the process)
        self.degraded = False
        self._rings: Dict[Any, List[_RingSlot]] = {}
        self._ring_pos: Dict[Any, int] = {}
        self._unpackers: Dict[Any, Callable] = {}
        # materialize the degraded-engines gauge at 0 so a /metrics scrape
        # sees the series before (and whether or not) anything degrades
        core_telemetry.gauge("io.feed.degraded_engines")

    def _obs_transfer(self, nbytes: float, dt: float, chunks: int) -> None:
        """Per-transfer registry instrumentation: latency + size
        histograms always; a `feed.transfer` child span when the calling
        thread is inside a trace (a served request's batch tick), so the
        device upload shows up in that request's `/trace/<id>` tree."""
        core_telemetry.histogram("io.feed.transfer.latency").observe(dt)
        core_telemetry.histogram(
            "io.feed.transfer.bytes",
            boundaries=core_telemetry.BYTE_BUCKETS).observe(nbytes)
        ctx = core_telemetry.current_context()
        if ctx is not None:
            core_telemetry.record_span("feed.transfer", ctx, dt,
                                       bytes=int(nbytes), chunks=chunks)

    # ---- guarded transfer ----------------------------------------------
    def _device_put(self, arr, sharding=None):
        """The one raw `jax.device_put` in the engine: named fault point +
        bounded retry with a tiny backoff (a transient link error costs
        microseconds, not the batch), run as a `StagePolicy` ladder."""
        import jax

        def attempt(a):
            fault_point("feed.device_put")
            # no-op unless enable_device_annotations() armed the
            # profiler hook: the transfer span itself is recorded
            # after the fact via record_span, which can't annotate
            with core_telemetry.device_annotation("feed.transfer"):
                return (jax.device_put(a, sharding)
                        if sharding is not None
                        else jax.device_put(a))

        return self._put_policy.run(attempt, arr)

    def _degrade(self, why: str):
        if not self.degraded:
            self.degraded = True
            core_telemetry.incr("feed.degraded")
            core_telemetry.gauge("io.feed.degraded_engines").inc()
            warnings.warn(f"DeviceFeed degraded to unpipelined transfers: {why}",
                          RuntimeWarning, stacklevel=3)

    # ---- the sharded direct-to-chip path (io/shard_put.py) -------------
    def _engine(self):
        if self._shard_engine is None:
            from .shard_put import ShardEngine

            # an explicit "sharded" strategy is a directive, not a hint:
            # drop the per-shard size floor so even small batches (tests,
            # the autotuner's sweeps) take the per-device path
            floor = 0 if self.shard_strategy == "sharded" else 1 << 12
            self._shard_engine = ShardEngine(policy=self._shard_policy,
                                             telemetry=self.telemetry,
                                             min_shard_bytes=floor)
        return self._shard_engine

    def _degrade_shard(self, why: str):
        """The shard rung of the ladder: sticky per-feed fall-back to the
        coalesced single-put path (which keeps ITS retry/degrade rungs)."""
        if not self.shard_degraded:
            self.shard_degraded = True
            core_telemetry.incr("feed.shard_degraded")
            warnings.warn(
                f"DeviceFeed sharded path degraded to coalesced: {why}",
                RuntimeWarning, stacklevel=3)

    def _try_sharded(self, arr: np.ndarray, sharding):
        """`arr` through the sharded engine, or None when this put is not
        eligible (strategy, degraded, uneven batch, single target) — the
        caller continues on the coalesced path.  Ineligibility of a
        genuinely multi-device put is counted as a fallback group: that
        is the `h2d_path="fallback"` signal bench and feed_bench report."""
        from .shard_put import ShardTransferError

        if self.shard_degraded or self.shard_strategy == "coalesced":
            return None
        if sharding is None:
            return None
        from .shard_put import shard_layout

        eng = self._engine()
        layout = shard_layout(sharding, arr.shape)
        if layout is None or len(layout) <= 1:
            # uneven batch (or a single-target sharding): only the former
            # is a genuine fall-off of the sharded path
            try:
                multi = len(sharding.addressable_devices) > 1
            except (AttributeError, TypeError):
                multi = False
            if multi:
                self.telemetry.add(fallback_groups=1)
                core_telemetry.incr("io.feed.shard.fallback")
            return None
        if arr.nbytes // len(layout) < eng.min_shard_bytes:
            # below the per-shard floor the fixed per-put cost wins:
            # coalescing is the DELIBERATE choice here, not a fallback
            return None
        try:
            out = eng.put_sharded(arr, sharding, layout)
        except ShardTransferError as e:
            self._degrade_shard(f"shard put failed after retries: {e}")
            self.telemetry.add(fallback_groups=1)
            core_telemetry.incr("io.feed.shard.fallback")
            return None
        self.telemetry.add(chunks_fed=1, groups=1)
        return out

    # ---- sharding helpers ----------------------------------------------
    def _dp(self) -> int:
        return self.mesh.shape["data"] if self.mesh is not None else 1

    def _chunk_sharding(self, ndim: int):
        if self.mesh is None:
            return None
        from ..parallel.mesh import batch_sharding

        return batch_sharding(self.mesh, ndim)

    def _packed_sharding(self, ndim: int):
        """Sharding for a [k, bs, ...] packed buffer: batch axis is dim 1."""
        if self.mesh is None:
            return None
        from ..parallel.mesh import batch_sharding

        return batch_sharding(self.mesh, ndim, batch_axis=1)

    # ---- single transfers ----------------------------------------------
    def put(self, arr, sharding=None, block: bool = False):
        """One counted `device_put`.  `block=True` waits for the transfer
        (bandwidth probes); otherwise dispatch is async like raw jax.
        Multi-device sharded puts that divide evenly ride the concurrent
        per-shard engine; everything else takes the coalesced path."""
        import jax

        arr = np.asarray(arr)
        out = self._try_sharded(arr, sharding)
        if out is not None:
            if block:
                jax.block_until_ready(out)
            return out
        t0 = time.perf_counter()
        out = self._device_put(arr, sharding)
        if block:
            jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        self.telemetry.add(bytes_moved=arr.nbytes, transfer_calls=1,
                           transfer_s=dt, chunks_fed=1, groups=1)
        self._obs_transfer(arr.nbytes, dt, 1)
        return out

    def put_group(self, arrays: Sequence[np.ndarray], shardings=None,
                  sharded_multi: bool = False):
        """Several host arrays -> device in ONE transfer when profitable.

        Arrays byte-pack into a single uint8 wire buffer (offset header)
        and are sliced/bitcast apart on device — one fixed per-transfer
        cost instead of len(arrays).  On a multi-device mesh a replicated
        byte buffer would multiply wire bytes, so unless the caller opts
        in (`sharded_multi` for replicated consumers), packing engages
        only single-device and the call degrades to per-array puts.

        Items may also be `ops.wire_codec.RLEPayload` (still-encoded
        chunks): the group then rides the compressed wire — one packed
        transfer of values + cumulative length tables, re-expanded ON
        DEVICE (Pallas page-walk kernel on TPU, XLA repeat elsewhere).
        """
        import jax

        from ..ops.wire_codec import RLEPayload

        if arrays and all(isinstance(a, RLEPayload) for a in arrays):
            return self._put_compressed(list(arrays))
        arrays = [np.ascontiguousarray(a) for a in arrays]
        if shardings is None:
            shardings = [None] * len(arrays)
        if len(arrays) == 1:
            return (self.put(arrays[0], shardings[0]),)
        multi = jax.device_count() > 1
        if multi and not sharded_multi and any(s is not None for s in shardings):
            return tuple(self.put(a, s) for a, s in zip(arrays, shardings))
        if self.degraded:
            return tuple(self.put(a, s) for a, s in zip(arrays, shardings))

        layout = []
        off = 0
        for a in arrays:
            layout.append((off, a.shape, a.dtype.str))
            off += -(-a.nbytes // _ALIGN) * _ALIGN
        total = max(off, _ALIGN)
        slot = self._acquire_slot(("bytes", total), (total,), np.uint8)
        for a, (o, _s, _d) in zip(arrays, layout):
            slot.buf[o:o + a.nbytes] = a.reshape(-1).view(np.uint8)
        t0 = time.perf_counter()
        try:
            packed = self._device_put(slot.buf)
        except Exception as e:  # noqa: BLE001 — degrade, then the safe path
            self._degrade(f"packed put_group failed after retries: {e}")
            return tuple(self.put(a, s) for a, s in zip(arrays, shardings))
        dt = time.perf_counter() - t0
        self.telemetry.add(bytes_moved=total, transfer_calls=1,
                           transfer_s=dt,
                           chunks_fed=len(arrays), groups=1,
                           coalesced_chunks=len(arrays))
        self._obs_transfer(total, dt, len(arrays))
        outs = self._unpack_bytes(packed, tuple(layout), shardings)
        # the slot is rewritten only after these outputs exist on device
        slot.fence = outs
        return outs

    def _put_compressed(self, payloads):
        """RLE-encoded chunks over the compressed wire: values + ends
        tables byte-pack into ONE transfer (the same wire buffer and
        fault/retry ladder as `put_group`), then each chunk is decoded
        back to its raw bytes on device (`ops.wire_codec.decode_bytes`)
        and bitcast/reshaped into shape.  A transfer that exhausts its
        retries — or an already-degraded feed — decodes on the HOST and
        rides plain per-chunk puts: the fallback costs wire bytes, never
        correctness."""
        from ..ops import wire_codec

        def host_fallback():
            outs = []
            for p in payloads:
                outs.append(self.put(wire_codec.decode_host(p)))
            return tuple(outs)

        if self.degraded:
            return host_fallback()
        wire: List[np.ndarray] = []
        for p in payloads:
            wire.append(p.values)
            wire.append(p.ends)
        layout = []
        off = 0
        for a in wire:
            layout.append((off, a.shape, a.dtype.str))
            off += -(-a.nbytes // _ALIGN) * _ALIGN
        total = max(off, _ALIGN)
        slot = self._acquire_slot(("bytes", total), (total,), np.uint8)
        for a, (o, _s, _d) in zip(wire, layout):
            slot.buf[o:o + a.nbytes] = a.reshape(-1).view(np.uint8)
        t0 = time.perf_counter()
        try:
            packed = self._device_put(slot.buf)
        except Exception as e:  # noqa: BLE001 — degrade, then the safe path
            self._degrade(f"compressed wire transfer failed after retries: {e}")
            return host_fallback()
        dt = time.perf_counter() - t0
        raw_bytes = sum(p.nbytes_raw for p in payloads)
        self.telemetry.add(bytes_moved=total, transfer_calls=1,
                           transfer_s=dt, chunks_fed=len(payloads),
                           groups=1, coalesced_chunks=len(payloads),
                           compressed_groups=1, wire_bytes_raw=raw_bytes,
                           wire_bytes_sent=total)
        self._obs_transfer(total, dt, len(payloads))
        core_telemetry.incr("io.feed.shard.compressed_groups")
        parts = self._unpack_bytes(packed, tuple(layout), None)
        use_pallas = wire_codec.rle_kernel_ok()
        outs = []
        for i, p in enumerate(payloads):
            v, e = parts[2 * i], parts[2 * i + 1]
            raw = wire_codec.decode_bytes(v, e, p.first_run, p.n_pad,
                                          use_pallas)
            outs.append(self._finish_decoded(raw, p))
        outs = tuple(outs)
        slot.fence = outs
        return outs

    def _finish_decoded(self, raw, payload):
        """Decoded uint8[n_pad] -> the chunk's dtype/shape on device; one
        cached jitted program per (n_pad, nbytes, dtype, shape)."""
        import jax

        key = ("rle", payload.n_pad, payload.nbytes_raw,
               payload.dtype.str, payload.shape)
        fn = self._unpackers.get(key)
        if fn is None:
            dt = payload.dtype
            n = payload.nbytes_raw // dt.itemsize
            shape = payload.shape

            def finish(buf):
                seg = buf[:n * dt.itemsize]
                if dt == np.uint8:
                    arr = seg
                else:
                    arr = jax.lax.bitcast_convert_type(
                        seg.reshape(n, dt.itemsize), dt)
                return arr.reshape(shape)

            fn = jax.jit(finish)
            self._unpackers[key] = fn
        return fn(raw)

    def stream(self, items: Iterable[Tuple[np.ndarray, ...]], shardings=None,
               sharded_multi: bool = False):
        """Prefetching transfer stream for sequential consumers (train
        loops): yields each item's device arrays while keeping up to
        `depth` later items' transfers already dispatched — slice t+1
        moves while the scanned epoch for slice t computes.  Each item
        (a tuple of host arrays) rides one packed transfer when the mesh
        is single-device (`put_group`)."""
        buf: deque = deque()
        t0 = time.perf_counter()
        for item in items:
            buf.append(self.put_group(tuple(item), shardings,
                                      sharded_multi=sharded_multi))
            while len(buf) > self.depth:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
        self.telemetry.add(wall_s=time.perf_counter() - t0)

    # ---- the pipelined chunk engine ------------------------------------
    def run(self, chunk_iter, compute_fn: Callable,
            greedy: bool = True) -> List[np.ndarray]:
        """Drive (chunk, n_valid) pairs through transfer + compute with
        decode/transfer/compute overlap; returns per-chunk host outputs
        trimmed to n_valid, in feed order.

        `chunk_iter` is either a plain iterable — it runs on ONE
        prefetch thread (`_IterSource`; decode/assembly overlap device
        compute) — or a `FeedSource` that owns its own production
        concurrency (HostPipeline's N decode workers feed the same
        consumer loop; io/pipeline.py).  Ready chunks coalesce into
        packed groups (same shape/dtype: one [k, bs, ...] buffer; mixed
        on a single device: one byte-packed buffer); each group is ONE
        `device_put`, split apart on device by a donated unpack program,
        and `compute_fn` is dispatched per chunk.  Up to `depth` groups
        are in flight; the oldest drains (async-fetched) when the window
        fills.

        greedy=True never waits for a fuller pack (latency-first; the
        transform path).  greedy=False waits until `coalesce` chunks are
        queued (or the producer is done) before forming each group —
        maximum amortization when total latency is what matters (bulk
        jobs, the microbench)."""
        import jax

        tel = self.telemetry
        t_wall = time.perf_counter()
        if isinstance(chunk_iter, FeedSource):
            source = chunk_iter
        else:
            source = _IterSource(chunk_iter,
                                 maxsize=max(4 * self.coalesce,
                                             2 * self.depth))
        source.start()

        results: List[np.ndarray] = []
        inflight: deque = deque()  # (ys, ns, slot) per group, feed order
        done = False
        leftover: Optional[Tuple[np.ndarray, int]] = None

        def drain_group():
            ys, ns, slot = inflight.popleft()
            t0 = time.perf_counter()
            for y, n in zip(ys, ns):
                results.append(np.asarray(y)[:n])
            tel.add(stall_drain_s=time.perf_counter() - t0)
            if slot is not None:
                slot.busy = False

        while not done or leftover is not None:
            # ---- collect the next group of ready chunks ----
            # a degraded engine forms singleton groups and keeps nothing
            # in flight (the safe unpipelined ladder rung; may flip
            # mid-run when a packed transfer exhausts its retries)
            coalesce_now = 1 if self.degraded else self.coalesce
            group: List[Tuple[np.ndarray, int]] = []
            gbytes = 0
            if leftover is not None:
                group.append(leftover)
                gbytes = leftover[0].nbytes
                leftover = None
            while len(group) < coalesce_now and gbytes < self.coalesce_bytes:
                if not group or (not greedy and not done):
                    t0 = time.perf_counter()
                    item = source.get()
                    tel.add(stall_decode_s=time.perf_counter() - t0)
                else:
                    try:
                        item = source.get_nowait()
                    except queue.Empty:
                        break
                if item is FEED_END:
                    done = True
                    break
                chunk, n = item
                if group and not self._can_pack(group[0][0], chunk):
                    leftover = (chunk, n)
                    break
                group.append((chunk, n))
                gbytes += chunk.nbytes
            if not group:
                continue

            # ---- one transfer for the whole group ----
            xs, slot = self._transfer_group(group)
            t0 = time.perf_counter()
            ys = []
            for x in xs:
                ys.append(compute_fn(x))
            for y in ys:
                try:
                    # start device->host DMA at dispatch so the fetch
                    # overlaps later groups instead of serializing at drain
                    y.copy_to_host_async()
                except (AttributeError, NotImplementedError):
                    pass
            # dispatch time; the blocked remainder of device compute
            # lands in stall_drain_s — the sum is the forward's
            # host-visible cost (bench.py's forward_ms)
            tel.add(compute_s=time.perf_counter() - t0)
            inflight.append((ys, [n for _c, n in group], slot))
            while len(inflight) > (0 if self.degraded else self.depth):
                drain_group()
        while inflight:
            drain_group()
        tel.add(wall_s=time.perf_counter() - t_wall)
        src_err = source.error()
        if src_err is not None:
            raise src_err
        return results

    # ---- packing internals ---------------------------------------------
    def _can_pack(self, a: np.ndarray, b: np.ndarray) -> bool:
        """Chunks pack together when same shape+dtype (array pack) or, on
        a single device, any shapes via the byte-packed wire (a sharded
        byte buffer cannot carry mixed batch axes across shards)."""
        if a.shape == b.shape and a.dtype == b.dtype:
            return True
        return self._dp() == 1 and (self.mesh is None
                                    or self.mesh.devices.size == 1)

    def _acquire_slot(self, key, shape, dtype) -> _RingSlot:
        """Ring slot for a packing buffer: `depth + 1` slots per wire
        shape, reused round-robin.  device_put may alias host memory
        zero-copy (CPU backend), so a busy slot must drain first and a
        fenced slot blocks on its unpacked outputs before rewrite."""
        import jax

        ring = self._rings.setdefault(key, [])
        if not ring:
            ring.extend(_RingSlot() for _ in range(self.depth + 1))
        pos = self._ring_pos.get(key, 0)
        self._ring_pos[key] = (pos + 1) % len(ring)
        slot = ring[pos]
        if slot.fence is not None:
            t0 = time.perf_counter()
            jax.block_until_ready(slot.fence)
            self.telemetry.add(stall_drain_s=time.perf_counter() - t0)
            slot.fence = None
        if slot.buf is None or slot.buf.shape != tuple(shape) \
                or slot.buf.dtype != dtype:
            slot.buf = np.empty(shape, dtype)
        return slot

    def _transfer_group(self, group):
        """ONE device_put for the group; returns (device chunks, ring slot
        or None).  Singletons skip packing entirely (no host copy).  A
        packed transfer that fails all its retries degrades the engine and
        the group falls back to per-chunk singleton transfers."""
        tel = self.telemetry

        def put_one(c):
            sh = self._chunk_sharding(c.ndim)
            t0 = time.perf_counter()
            x = self._device_put(c, sh)
            dt = time.perf_counter() - t0
            tel.add(bytes_moved=c.nbytes, transfer_calls=1,
                    transfer_s=dt, chunks_fed=1, groups=1)
            self._obs_transfer(c.nbytes, dt, 1)
            return x

        chunks = [c for c, _n in group]
        k = len(chunks)
        if k == 1 or self.degraded:
            return [put_one(c) for c in chunks], None

        first = chunks[0]
        homogeneous = all(c.shape == first.shape and c.dtype == first.dtype
                          for c in chunks)
        if homogeneous:
            key = ("pack", k, first.shape, first.dtype.str)
            slot = self._acquire_slot(key, (k,) + first.shape, first.dtype)
            # a slot stays busy until its group drains; _acquire_slot only
            # hands out free slots because the ring has depth+1 entries
            # and the in-flight window is depth
            slot.busy = True
            for i, c in enumerate(chunks):
                slot.buf[i] = c
            t0 = time.perf_counter()
            sh = self._packed_sharding(slot.buf.ndim)
            try:
                packed = self._device_put(slot.buf, sh)
            except Exception as e:  # noqa: BLE001 — degrade, then safe path
                slot.busy = False
                self._degrade(f"packed stack transfer failed after retries: {e}")
                return [put_one(c) for c in chunks], None
            dt = time.perf_counter() - t0
            tel.add(bytes_moved=slot.buf.nbytes, transfer_calls=1,
                    transfer_s=dt, chunks_fed=k, groups=1,
                    coalesced_chunks=k)
            self._obs_transfer(slot.buf.nbytes, dt, k)
            xs = list(self._unpack_stack(packed, k, first.shape,
                                         first.dtype.str))
            return xs, slot

        # mixed shapes/dtypes: byte-pack with an offset header (single
        # device only — _can_pack gates this path)
        layout = []
        off = 0
        for c in chunks:
            layout.append((off, c.shape, c.dtype.str))
            off += -(-c.nbytes // _ALIGN) * _ALIGN
        total = off
        slot = self._acquire_slot(("bytes", total), (total,), np.uint8)
        slot.busy = True
        for c, (o, _s, _d) in zip(chunks, layout):
            slot.buf[o:o + c.nbytes] = c.reshape(-1).view(np.uint8)
        t0 = time.perf_counter()
        try:
            packed = self._device_put(slot.buf)
        except Exception as e:  # noqa: BLE001 — degrade, then safe path
            slot.busy = False
            self._degrade(f"packed byte transfer failed after retries: {e}")
            return [put_one(c) for c in chunks], None
        dt = time.perf_counter() - t0
        tel.add(bytes_moved=total, transfer_calls=1,
                transfer_s=dt, chunks_fed=k, groups=1, coalesced_chunks=k)
        self._obs_transfer(total, dt, k)
        xs = list(self._unpack_bytes(packed, tuple(layout), None))
        return xs, slot

    def _unpack_stack(self, packed, k: int, shape, dtype_str: str):
        """Split a [k, bs, ...] packed buffer into k chunks on device —
        one jitted program per (k, shape) signature, input DONATED so the
        staging HBM is released/aliased at the split."""
        import jax

        key = ("stack", k, tuple(shape), dtype_str)
        fn = self._unpackers.get(key)
        if fn is None:
            out_sh = self._chunk_sharding(len(shape))

            def split(p):
                return tuple(p[i] for i in range(k))

            kw = {"donate_argnums": (0,)}
            if out_sh is not None:
                kw["out_shardings"] = (out_sh,) * k
            fn = jax.jit(split, **kw)
            self._unpackers[key] = fn
            return _first_call(fn, packed)
        return fn(packed)

    def _unpack_bytes(self, packed, layout, shardings):
        """Slice + bitcast + reshape the byte-packed wire buffer back into
        its arrays on device — one jitted program per layout signature
        (offsets are static; serving's per-tick layout is constant, so
        this compiles once)."""
        import jax

        key = ("bytes", layout, tuple(str(s) for s in shardings or ()))
        fn = self._unpackers.get(key)
        if fn is None:
            def unpack(buf):
                outs = []
                for off, shape, dstr in layout:
                    dt = np.dtype(dstr)
                    n = int(np.prod(shape, dtype=np.int64))
                    seg = buf[off:off + n * dt.itemsize]
                    if dt == np.uint8:
                        arr = seg
                    else:
                        arr = jax.lax.bitcast_convert_type(
                            seg.reshape(n, dt.itemsize), dt)
                    outs.append(arr.reshape(shape))
                return tuple(outs)

            kw: Dict[str, Any] = {"donate_argnums": (0,)}
            if shardings is not None and any(s is not None for s in shardings):
                kw["out_shardings"] = tuple(shardings)
            fn = jax.jit(unpack, **kw)
            self._unpackers[key] = fn
            return _first_call(fn, packed)
        return fn(packed)

    # ---- the flow adapter ----------------------------------------------
    def stage(self, workers: int = 1,
              credits: Optional[int] = None) -> "H2DStage":
        """This feed's h2d hop as a graftflow `Stage`, for credit-bounded
        decode -> assemble -> h2d graphs (core/flow.py)."""
        return H2DStage(self, workers=workers, credits=credits)


class H2DStage(Stage):
    """DeviceFeed's h2d hop as a registered flow stage: each item is one
    host array (or a tuple of arrays packed into one transfer) moved
    through the feed's guarded put path — the `feed.device_put`
    StagePolicy retry ladder and the degrade-to-singletons terminal rung
    ride underneath unchanged.  A meshed feed's stage additionally
    shards data-divisible batches straight across the mesh (the
    per-device engine in io/shard_put.py), so the `feed.shard_put`
    ladder and the sticky shard->coalesced degrade rung are exercised by
    credit-bounded graphs too.  The bounded credit budget is the staging
    discipline as a declared number: at most `credits` chunks staged
    host-side per graph (lint rule G405 holds every registered Stage
    subclass to one)."""

    name = "h2d"
    credits = 4

    def __init__(self, feed: Optional[DeviceFeed] = None,
                 workers: int = 1, credits: Optional[int] = None):
        super().__init__(workers=workers, credits=credits)
        self.feed = feed if feed is not None else DeviceFeed()

    def process(self, value):
        if isinstance(value, (tuple, list)):
            return self.feed.put_group(
                tuple(np.asarray(a) for a in value))
        arr = np.asarray(value)
        sharding = None
        if self.feed.mesh is not None and arr.ndim \
                and arr.shape[0] % self.feed._dp() == 0:
            sharding = self.feed._chunk_sharding(arr.ndim)
        return self.feed.put(arr, sharding)
