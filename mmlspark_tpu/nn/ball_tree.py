"""Serializable ball trees for maximum-inner-product search.

Reference: core nn/BallTree.scala:31-271 — `BallTree(keys, values)` with
`findMaximumInnerProducts(query, k)` and `ConditionalBallTree` whose queries
carry a set of allowed labels (label-filtered NN for conditional image
matching).  The tree is the *host* path (single-query serving); bulk
transforms use the batched MXU matmul path in `nn/knn.py` — on TPU a dense
`Q @ K^T` + `top_k` beats pointer-chasing for any realistic batch.

Build: recursive median split along the dimension of maximal spread; each
node stores (centroid mu, radius r) so the max attainable inner product in a
ball is bounded by `q . mu + |q| * r` (Cauchy–Schwarz), the same bound the
reference uses for branch pruning.
"""
from __future__ import annotations

import heapq
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BallTree", "ConditionalBallTree", "BestMatch"]


class BestMatch:
    """A single query result: item index, payload value, inner product."""

    __slots__ = ("index", "value", "distance")

    def __init__(self, index: int, value: Any, distance: float):
        self.index = index
        self.value = value
        self.distance = distance

    def __repr__(self):
        return f"BestMatch(index={self.index}, value={self.value!r}, distance={self.distance:.6g})"

    def __eq__(self, other):
        return (
            isinstance(other, BestMatch)
            and self.index == other.index
            and abs(self.distance - other.distance) < 1e-9
        )


class _Node:
    __slots__ = ("mu", "radius", "lo", "hi", "left", "right")

    def __init__(self, mu, radius, lo, hi, left=None, right=None):
        self.mu = mu          # ball centroid
        self.radius = radius  # max distance from centroid to member
        self.lo = lo          # [lo, hi) slice into the permuted index array
        self.hi = hi
        self.left = left
        self.right = right

    @property
    def is_leaf(self):
        return self.left is None


def _build(keys: np.ndarray, perm: np.ndarray, lo: int, hi: int, leaf_size: int) -> _Node:
    pts = keys[perm[lo:hi]]
    mu = pts.mean(axis=0)
    radius = float(np.sqrt(((pts - mu) ** 2).sum(axis=1).max())) if hi > lo else 0.0
    node = _Node(mu, radius, lo, hi)
    if hi - lo <= leaf_size:
        return node
    spread = pts.max(axis=0) - pts.min(axis=0)
    dim = int(np.argmax(spread))
    order = np.argsort(pts[:, dim], kind="stable")
    perm[lo:hi] = perm[lo:hi][order]
    mid = (lo + hi) // 2
    if mid == lo or mid == hi:  # all points identical along every axis
        return node
    node.left = _build(keys, perm, lo, mid, leaf_size)
    node.right = _build(keys, perm, mid, hi, leaf_size)
    return node


class BallTree:
    """Maximum-inner-product ball tree (BallTree.scala:31-271).

    `keys`: (N, D) float array.  `values`: optional payload per key (defaults
    to the integer index, like the reference's `values: IndexedSeq[V]`).
    """

    def __init__(
        self,
        keys: np.ndarray,
        values: Optional[Sequence[Any]] = None,
        leaf_size: int = 50,
    ):
        self.keys = np.ascontiguousarray(np.asarray(keys, dtype=np.float64))
        if self.keys.ndim != 2:
            raise ValueError(f"keys must be (N, D), got {self.keys.shape}")
        n = len(self.keys)
        self.values: List[Any] = list(values) if values is not None else list(range(n))
        if len(self.values) != n:
            raise ValueError("values length must match keys")
        self.leaf_size = int(leaf_size)
        self._perm = np.arange(n)
        self._root = _build(self.keys, self._perm, 0, n, self.leaf_size) if n else None

    # -- query ----------------------------------------------------------
    def _upper_bound(self, q: np.ndarray, qnorm: float, node: _Node) -> float:
        return float(q @ node.mu) + qnorm * node.radius

    def find_maximum_inner_products(
        self, query: np.ndarray, k: int = 1, allowed: Optional[set] = None,
        labels: Optional[np.ndarray] = None,
    ) -> List[BestMatch]:
        """Top-k by inner product; `allowed`/`labels` implement the
        conditional (label-filtered) variant."""
        if self._root is None or k <= 0:
            return []
        q = np.asarray(query, dtype=np.float64).ravel()
        qnorm = float(np.linalg.norm(q))
        heap: List[Tuple[float, int]] = []  # min-heap of (ip, index)

        def visit(node: _Node):
            if len(heap) == k and self._upper_bound(q, qnorm, node) <= heap[0][0]:
                return  # prune: ball cannot beat current k-th best
            if node.is_leaf:
                idx = self._perm[node.lo:node.hi]
                if allowed is not None:
                    mask = np.fromiter(
                        (labels[i] in allowed for i in idx), bool, count=len(idx)
                    )
                    idx = idx[mask]
                    if not len(idx):
                        return
                ips = self.keys[idx] @ q
                for i, ip in zip(idx, ips):
                    if len(heap) < k:
                        heapq.heappush(heap, (float(ip), int(i)))
                    elif ip > heap[0][0]:
                        heapq.heapreplace(heap, (float(ip), int(i)))
                return
            # visit the more promising child first for earlier pruning
            bl = self._upper_bound(q, qnorm, node.left)
            br = self._upper_bound(q, qnorm, node.right)
            first, second = (
                (node.left, node.right) if bl >= br else (node.right, node.left)
            )
            visit(first)
            visit(second)

        visit(self._root)
        out = sorted(heap, key=lambda t: -t[0])
        return [BestMatch(i, self.values[i], ip) for ip, i in out]

    def __len__(self):
        return len(self.keys)

    # -- serialization (pickled as a ComplexParam; rebuild on load) ------
    def __getstate__(self):
        return {"keys": self.keys, "values": self.values, "leaf_size": self.leaf_size}

    def __setstate__(self, state):
        self.__init__(state["keys"], state["values"], state["leaf_size"])


class ConditionalBallTree(BallTree):
    """Label-filtered ball tree (BallTree.scala ConditionalBallTree).

    Queries carry a set of allowed labels; only items whose label is in the
    set compete for the top-k.
    """

    def __init__(
        self,
        keys: np.ndarray,
        values: Optional[Sequence[Any]] = None,
        labels: Optional[Sequence[Any]] = None,
        leaf_size: int = 50,
    ):
        super().__init__(keys, values, leaf_size)
        if labels is None:
            raise ValueError("ConditionalBallTree requires labels")
        self.labels = np.asarray(list(labels), dtype=object)
        if len(self.labels) != len(self.keys):
            raise ValueError("labels length must match keys")

    def find_maximum_inner_products(
        self, query: np.ndarray, k: int = 1, allowed: Optional[set] = None, labels=None
    ) -> List[BestMatch]:
        if allowed is None:
            raise ValueError("conditional query requires the set of allowed labels")
        return super().find_maximum_inner_products(
            query, k, allowed=set(allowed), labels=self.labels
        )

    def __getstate__(self):
        d = super().__getstate__()
        d["labels"] = self.labels
        return d

    def __setstate__(self, state):
        self.__init__(state["keys"], state["values"], state["labels"], state["leaf_size"])
