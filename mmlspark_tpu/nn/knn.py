"""KNN estimators: batched maximum-inner-product search on the MXU.

Reference: core nn/KNN.scala:48-126 (KNN broadcasts a BallTree, maps rows to
`findMaximumInnerProducts(q, k)`) and nn/ConditionalKNN.scala:31-120 (adds a
per-query set of allowed labels).  TPU-first redesign: bulk transform is a
dense `Q @ K^T` scored on the MXU + `lax.top_k` — a batched matmul saturates
the systolic array where the reference's per-row tree walk was pointer-bound;
the serialized BallTree (nn/ball_tree.py) remains the single-query host path
for serving.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.registry import register_stage
from ..core.schema import Table, features_matrix as _matrix
from .ball_tree import BallTree, ConditionalBallTree

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]

_CHUNK = 4096  # query rows per device batch (bounds the (B, N) score matrix)




@partial(jax.jit, static_argnames=("k",))
def _topk_scores(keys: jnp.ndarray, queries: jnp.ndarray, k: int):
    scores = queries @ keys.T  # (B, N) on the MXU
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("k",))
def _topk_scores_masked(keys, queries, mask, k: int):
    scores = queries @ keys.T
    scores = jnp.where(mask, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _batched_topk(keys_np, queries_np, k, mask_np=None):
    """Chunked device top-k; returns (values (B,k), indices (B,k)) numpy."""
    if len(queries_np) == 0:
        return (np.zeros((0, k), np.float32), np.zeros((0, k), np.int32))
    keys = jnp.asarray(keys_np)
    vals, idxs = [], []
    for lo in range(0, len(queries_np), _CHUNK):
        q = jnp.asarray(queries_np[lo: lo + _CHUNK])
        if mask_np is None:
            v, i = _topk_scores(keys, q, k)
        else:
            m = jnp.asarray(mask_np[lo: lo + _CHUNK])
            v, i = _topk_scores_masked(keys, q, m, k)
        vals.append(np.asarray(v))
        idxs.append(np.asarray(i))
    return np.concatenate(vals), np.concatenate(idxs)


class _KNNParams:
    features_col = Param("query features column", default="features")
    values_col = Param("payload column returned with each match", default="values")
    output_col = Param("output column of matches", default="output")
    k = Param("number of matches", default=5, converter=TypeConverters.to_int)
    leaf_size = Param("ball-tree leaf size", default=50, converter=TypeConverters.to_int)


@register_stage
class KNN(Estimator, _KNNParams):
    """Fit memorizes the (features, values) index table; transform scores
    queries against it (KNN.scala:48)."""

    def _fit(self, table: Table) -> "KNNModel":
        keys = _matrix(table[self.features_col])
        values = list(table[self.values_col])
        return KNNModel(
            features_col=self.features_col,
            output_col=self.output_col,
            k=self.k,
            ball_tree=BallTree(keys, values, leaf_size=self.leaf_size),
        )


@register_stage
class KNNModel(Model):
    features_col = Param("query features column", default="features")
    output_col = Param("output column of matches", default="output")
    k = Param("number of matches", default=5, converter=TypeConverters.to_int)
    ball_tree = ComplexParam("fitted BallTree (host single-query path)")

    def _transform(self, table: Table) -> Table:
        tree: BallTree = self.ball_tree
        queries = _matrix(table[self.features_col])
        k = min(self.k, len(tree))
        vals, idxs = _batched_topk(tree.keys.astype(np.float32), queries, k)
        out = np.empty(len(queries), dtype=object)
        for r in range(len(queries)):
            out[r] = [
                {"value": tree.values[int(i)], "distance": float(v)}
                for v, i in zip(vals[r], idxs[r])
            ]
        return table.with_column(self.output_col, out)

    def query_one(self, point: np.ndarray, k: Optional[int] = None):
        """Single-query host path via the ball tree (serving latency path)."""
        return self.ball_tree.find_maximum_inner_products(point, k or self.k)

    def transform_schema(self, columns: List[str]) -> List[str]:
        if self.features_col not in columns:
            raise ValueError(f"missing features column '{self.features_col}'")
        return columns + [self.output_col]


@register_stage
class ConditionalKNN(Estimator, _KNNParams):
    """KNN whose index rows carry labels and whose queries carry the set of
    labels they may match (ConditionalKNN.scala:31)."""

    label_col = Param("index label column", default="labels")

    def _fit(self, table: Table) -> "ConditionalKNNModel":
        keys = _matrix(table[self.features_col])
        values = list(table[self.values_col])
        labels = list(table[self.label_col])
        return ConditionalKNNModel(
            features_col=self.features_col,
            output_col=self.output_col,
            conditioner_col=self.conditioner_col,
            k=self.k,
            ball_tree=ConditionalBallTree(keys, values, labels, leaf_size=self.leaf_size),
        )

    conditioner_col = Param("query column holding the allowed-label set", default="conditioner")


@register_stage
class ConditionalKNNModel(Model):
    features_col = Param("query features column", default="features")
    output_col = Param("output column of matches", default="output")
    conditioner_col = Param("query column holding the allowed-label set", default="conditioner")
    k = Param("number of matches", default=5, converter=TypeConverters.to_int)
    ball_tree = ComplexParam("fitted ConditionalBallTree")

    def _transform(self, table: Table) -> Table:
        tree: ConditionalBallTree = self.ball_tree
        queries = _matrix(table[self.features_col])
        conditioners = table[self.conditioner_col]
        k = min(self.k, len(tree))
        # vectorized label filter: code labels to ints once, build (B, N) mask
        levels = {v: i for i, v in enumerate(dict.fromkeys(tree.labels.tolist()))}
        codes = np.array([levels[v] for v in tree.labels.tolist()], dtype=np.int32)
        mask = np.zeros((len(queries), len(tree)), dtype=bool)
        for r, cond in enumerate(conditioners):
            allowed = {levels[c] for c in cond if c in levels}
            if allowed:
                mask[r] = np.isin(codes, list(allowed))
        vals, idxs = _batched_topk(tree.keys.astype(np.float32), queries, k, mask)
        out = np.empty(len(queries), dtype=object)
        for r in range(len(queries)):
            matches = []
            for v, i in zip(vals[r], idxs[r]):
                if not np.isfinite(v):
                    continue  # fewer than k items matched the conditioner
                matches.append(
                    {
                        "value": tree.values[int(i)],
                        "distance": float(v),
                        "label": tree.labels[int(i)],
                    }
                )
            out[r] = matches
        return table.with_column(self.output_col, out)

    def query_one(self, point: np.ndarray, allowed: set, k: Optional[int] = None):
        return self.ball_tree.find_maximum_inner_products(point, k or self.k, allowed=allowed)

    def transform_schema(self, columns: List[str]) -> List[str]:
        for c in (self.features_col, self.conditioner_col):
            if c not in columns:
                raise ValueError(f"missing column '{c}'")
        return columns + [self.output_col]
