"""Nearest-neighbor package: BallTree structures + KNN estimators.

Reference: core nn/ — BallTree.scala:31-271 (serializable BallTree /
ConditionalBallTree with label filtering), KNN.scala:48-126,
ConditionalKNN.scala:31-120 (estimators broadcasting the tree).
"""
from .ball_tree import BallTree, ConditionalBallTree
from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel

__all__ = [
    "BallTree",
    "ConditionalBallTree",
    "KNN",
    "KNNModel",
    "ConditionalKNN",
    "ConditionalKNNModel",
]
