"""MiniBatch stages: row streams <-> batch rows.

Reference: core stages/MiniBatchTransformer.scala:15-229 —
`FixedMiniBatchTransformer` (:47, optional double buffering),
`DynamicMiniBatchTransformer` (:71), `TimeIntervalMiniBatchTransformer` (:145),
`FlattenBatch` (:181), `HasMiniBatcher` mixin (:102).

A "batch row" holds, per column, the stacked values of `batch_size` input rows:
dense numeric columns stack to `[B, ...]` numpy arrays (directly
`device_put`-able); object columns become lists.  This is the host half of the
TPU feed path: MiniBatch -> device_put -> jitted forward -> FlattenBatch.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..core.batching import FixedBufferedBatcher, time_interval_batcher
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.registry import register_stage
from ..core.schema import Table

__all__ = [
    "FixedMiniBatchTransformer",
    "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer",
    "FlattenBatch",
    "HasMiniBatcher",
]


def _stack_batch(table: Table, start: int, stop: int) -> dict:
    row = {}
    for name in table.column_names:
        col = table.columns[name]
        chunk = col[start:stop]
        if chunk.dtype == object:
            row[name] = list(chunk)
        else:
            row[name] = np.asarray(chunk)
    return row


def _batches_to_table(batch_rows: List[dict], names: List[str]) -> Table:
    cols = {}
    for n in names:
        arr = np.empty(len(batch_rows), dtype=object)
        for i, r in enumerate(batch_rows):
            arr[i] = r[n]
        cols[n] = arr
    return Table(cols)


class _MiniBatchBase(Transformer):
    def _batch_bounds(self, table: Table):
        raise NotImplementedError

    def _transform(self, table: Table) -> Table:
        names = table.column_names
        rows = [
            _stack_batch(table, a, b) for a, b in self._batch_bounds(table)
        ]
        return _batches_to_table(rows, names)


@register_stage
class FixedMiniBatchTransformer(_MiniBatchBase):
    """Fixed-size minibatches; `buffered` prefetches batches on a background
    thread (double buffering the host side of the device feed).
    Reference: MiniBatchTransformer.scala:47.
    """

    batch_size = Param("rows per batch", default=32, converter=TypeConverters.to_int)
    # `buffered` is API parity with the reference; on a materialized Table the
    # output is eager either way, so prefetch would add no overlap here.  The
    # streaming double-buffer lives in core.batching.FixedBufferedBatcher and
    # the TPUModel device feed.
    buffered = Param("kept for API parity (Tables are materialized)", default=False,
                     converter=TypeConverters.to_bool)
    max_buffer_size = Param("max buffered batches", default=2,
                            converter=TypeConverters.to_int)

    def _transform(self, table: Table) -> Table:
        names = table.column_names
        bounds = [
            (s, min(s + self.batch_size, table.num_rows))
            for s in range(0, table.num_rows, self.batch_size)
        ]
        rows = [_stack_batch(table, a, b) for a, b in bounds]
        return _batches_to_table(rows, names)


@register_stage
class DynamicMiniBatchTransformer(_MiniBatchBase):
    """Drain-queue batching: batch size adapts to consumer speed.  On a
    materialized table this degenerates to one batch (all available rows are
    drained at once) — matching the reference's semantics on a static
    partition.  Reference: MiniBatchTransformer.scala:71.
    """

    max_batch_size = Param("cap on dynamic batch size", default=2**30,
                           converter=TypeConverters.to_int)

    def _batch_bounds(self, table: Table):
        n = table.num_rows
        cap = self.max_batch_size
        return [(s, min(s + cap, n)) for s in range(0, n, cap)]


@register_stage
class TimeIntervalMiniBatchTransformer(_MiniBatchBase):
    """Flush a batch every `interval_ms` while rows stream in.
    Reference: MiniBatchTransformer.scala:145.
    """

    interval_ms = Param("flush interval in ms", default=1000,
                        converter=TypeConverters.to_int)
    max_batch_size = Param("cap on batch size", default=2**30,
                           converter=TypeConverters.to_int)

    def _transform(self, table: Table) -> Table:
        names = table.column_names
        idx_batches = time_interval_batcher(
            range(table.num_rows), self.interval_ms, self.max_batch_size
        )
        rows = []
        for idxs in idx_batches:
            sub = table.take(np.asarray(idxs))
            rows.append(_stack_batch(sub, 0, sub.num_rows))
        return _batches_to_table(rows, names)


@register_stage
class FlattenBatch(Transformer):
    """Inverse of minibatching: explode each batch row back into scalar rows.
    Reference: MiniBatchTransformer.scala:181.
    """

    def _transform(self, table: Table) -> Table:
        names = table.column_names
        out_cols: dict = {n: [] for n in names}
        for i in range(table.num_rows):
            lengths = set()
            vals = {}
            for n in names:
                v = table.columns[n][i]
                vals[n] = v
                if isinstance(v, (list, np.ndarray)):
                    lengths.add(len(v))
            if len(lengths) > 1:
                raise ValueError(
                    f"FlattenBatch: batch row {i} has mismatched column lengths "
                    f"{sorted(lengths)}; refusing to silently misalign rows"
                )
            size = lengths.pop() if lengths else 1
            for n in names:
                v = vals[n]
                if isinstance(v, (list, np.ndarray)) and len(v) == size:
                    out_cols[n].extend(list(v))
                else:
                    out_cols[n].extend([v] * size)
        cols = {}
        for n in names:
            vals = out_cols[n]
            if vals and isinstance(vals[0], np.ndarray) and all(
                isinstance(v, np.ndarray) and v.shape == vals[0].shape and v.dtype == vals[0].dtype
                for v in vals
            ) and vals[0].dtype != object:
                cols[n] = np.stack(vals)
            else:
                cols[n] = vals
        return Table(cols)


class HasMiniBatcher:
    """Mixin param: stages that internally minibatch (e.g. TPUModel).
    Reference: MiniBatchTransformer.scala:102."""

    mini_batcher = Param("minibatching strategy stage", default=None)

    def get_mini_batcher(self) -> Transformer:
        return self.mini_batcher or FixedMiniBatchTransformer()
