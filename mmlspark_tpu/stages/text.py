"""Text plumbing stages: trie-based mapping + unicode normalization.

Reference: core stages/TextPreprocessor.scala:17 (Trie + TextPreprocessor),
stages/UnicodeNormalize.scala.
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.registry import register_stage
from ..core.schema import Table

__all__ = ["Trie", "TextPreprocessor", "UnicodeNormalize"]


class Trie:
    """Longest-match string-mapping trie (TextPreprocessor.scala:17)."""

    def __init__(self, mapping: Optional[Dict[str, str]] = None):
        self.children: Dict[str, "Trie"] = {}
        self.value: Optional[str] = None
        for k, v in (mapping or {}).items():
            self.put(k, v)

    def put(self, key: str, value: str) -> None:
        node = self
        for ch in key:
            node = node.children.setdefault(ch, Trie())
        node.value = value

    def map_text(self, text: str) -> str:
        out: List[str] = []
        i = 0
        n = len(text)
        while i < n:
            node = self
            best_val, best_len = None, 0
            j = i
            while j < n and text[j] in node.children:
                node = node.children[text[j]]
                j += 1
                if node.value is not None:
                    best_val, best_len = node.value, j - i
            if best_val is not None:
                out.append(best_val)
                i += best_len
            else:
                out.append(text[i])
                i += 1
        return "".join(out)


@register_stage
class TextPreprocessor(Transformer):
    input_col = Param("input text column")
    output_col = Param("output text column")
    map = ComplexParam("substring -> replacement dict")
    normalize_func = Param("optional: lower|upper|NFC|NFKC", default=None)

    def _transform(self, table: Table) -> Table:
        trie = Trie(self.map or {})
        norm = self.normalize_func
        out = []
        for s in table[self.input_col]:
            s = str(s)
            if norm in ("NFC", "NFKC", "NFD", "NFKD"):
                s = unicodedata.normalize(norm, s)
            elif norm == "lower":
                s = s.lower()
            elif norm == "upper":
                s = s.upper()
            out.append(trie.map_text(s))
        return table.with_column(self.output_col, out)


@register_stage
class UnicodeNormalize(Transformer):
    input_col = Param("input text column")
    output_col = Param("output text column")
    form = Param("NFC|NFD|NFKC|NFKD", default="NFKD")
    lower = Param("casefold output", default=True, converter=TypeConverters.to_bool)

    def _transform(self, table: Table) -> Table:
        out = []
        for s in table[self.input_col]:
            s = unicodedata.normalize(self.form, str(s))
            out.append(s.lower() if self.lower else s)
        return table.with_column(self.output_col, out)
