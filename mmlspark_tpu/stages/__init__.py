from .basic import (
    Cacher,
    ClassBalancer,
    ClassBalancerModel,
    DropColumns,
    EnsembleByKey,
    Explode,
    MultiColumnAdapter,
    PartitionConsolidator,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    Timer,
    TimerModel,
    UDFTransformer,
)
from .batching import (
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    HasMiniBatcher,
    TimeIntervalMiniBatchTransformer,
)
from .text import TextPreprocessor, Trie, UnicodeNormalize
