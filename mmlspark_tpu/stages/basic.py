"""Plumbing transformers: the fully-serializable pipeline stage toolbox.

Reference: core stages/*.scala — DropColumns, SelectColumns, RenameColumn,
Repartition, Cacher, Explode, UDFTransformer (UDFTransformer.scala:26),
MultiColumnAdapter (:19), EnsembleByKey (:20), ClassBalancer (:25),
SummarizeData (:101), Timer (:55), StratifiedRepartition (:31),
PartitionConsolidator (PartitionConsolidator.scala:22).
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..core.registry import register_stage
from ..core.schema import Table
from ..core.shared import shared_singleton

__all__ = [
    "DropColumns",
    "SelectColumns",
    "RenameColumn",
    "Repartition",
    "Cacher",
    "Explode",
    "UDFTransformer",
    "MultiColumnAdapter",
    "EnsembleByKey",
    "ClassBalancer",
    "ClassBalancerModel",
    "SummarizeData",
    "Timer",
    "TimerModel",
    "StratifiedRepartition",
    "PartitionConsolidator",
]


@register_stage
class DropColumns(Transformer):
    cols = Param("columns to drop", default=None, converter=TypeConverters.to_list_str)

    def __init__(self, cols: Optional[List[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set(cols=cols)

    def _transform(self, table: Table) -> Table:
        return table.drop(*(self.cols or []))

    def transform_schema(self, columns: List[str]) -> List[str]:
        drop = set(self.cols or [])
        missing = drop - set(columns)
        if missing:
            raise ValueError(f"DropColumns: missing columns {sorted(missing)}")
        return [c for c in columns if c not in drop]


@register_stage
class SelectColumns(Transformer):
    cols = Param("columns to keep", default=None, converter=TypeConverters.to_list_str)

    def __init__(self, cols: Optional[List[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set(cols=cols)

    def _transform(self, table: Table) -> Table:
        return table.select(self.cols or [])

    def transform_schema(self, columns: List[str]) -> List[str]:
        missing = set(self.cols or []) - set(columns)
        if missing:
            raise ValueError(f"SelectColumns: missing columns {sorted(missing)}")
        return list(self.cols or [])


@register_stage
class RenameColumn(Transformer):
    input_col = Param("source column")
    output_col = Param("target column")

    def _transform(self, table: Table) -> Table:
        return table.rename({self.input_col: self.output_col})


@register_stage
class Repartition(Transformer):
    """Sets the shard-count hint used when sharding a table over devices.
    In Spark this physically repartitions; here partitioning is logical —
    `num_partitions` is recorded in table meta for downstream shard-aware
    stages.  Reference: stages/Repartition.scala.
    """

    n = Param("number of partitions", default=1, converter=TypeConverters.to_int)

    def _transform(self, table: Table) -> Table:
        return table.with_meta("__partitioning__", {"num_partitions": self.n})


@register_stage
class Cacher(Transformer):
    """Materialization point.  Columnar tables are already materialized, so
    this is an (intentional) identity kept for pipeline parity.
    Reference: stages/Cacher.scala."""

    def _transform(self, table: Table) -> Table:
        return table


@register_stage
class Explode(Transformer):
    """One output row per element of a list-typed column; other columns are
    repeated.  Reference: stages/Explode.scala."""

    input_col = Param("column of sequences")

    def _transform(self, table: Table) -> Table:
        col = table[self.input_col]
        counts = [len(v) for v in col]
        idx = np.repeat(np.arange(table.num_rows), counts)
        exploded = [x for v in col for x in v]
        out = table.take(idx)
        return out.with_column(self.input_col, exploded)


@register_stage
class UDFTransformer(Transformer):
    """Apply a python function to one column (or a row-dict for multi-input).
    Reference: stages/UDFTransformer.scala:26."""

    input_col = Param("input column", default=None)
    input_cols = Param("input columns (row-dict mode)", default=None)
    output_col = Param("output column")
    udf = ComplexParam("value(s) -> value callable")

    def _transform(self, table: Table) -> Table:
        fn = self.udf
        if self.input_col is not None:
            out = [fn(v) for v in table[self.input_col]]
        else:
            cols = [table[c] for c in self.input_cols]
            out = [fn(*vals) for vals in zip(*cols)]
        return table.with_column(self.output_col, out)


@register_stage
class MultiColumnAdapter(Transformer):
    """Replicate a single-column stage across many columns.
    Reference: stages/MultiColumnAdapter.scala:19."""

    base_stage = ComplexParam("stage with input_col/output_col params")
    input_cols = Param("input columns", converter=TypeConverters.to_list_str)
    output_cols = Param("output columns", converter=TypeConverters.to_list_str)

    def _transform(self, table: Table) -> Table:
        for i, o in zip(self.input_cols, self.output_cols):
            stage = self.base_stage.copy({"input_col": i, "output_col": o})
            stage.uid = f"{self.base_stage.uid}_{i}"
            table = stage.transform(table)
        return table


@register_stage
class EnsembleByKey(Transformer):
    """Group rows by key column(s) and average numeric/vector columns.
    Reference: stages/EnsembleByKey.scala:20."""

    keys = Param("key columns", converter=TypeConverters.to_list_str)
    cols = Param("value columns to average", converter=TypeConverters.to_list_str)
    col_names = Param("output names", default=None)
    collapse_group = Param("one row per group", default=True,
                           converter=TypeConverters.to_bool)

    def _transform(self, table: Table) -> Table:
        keys = self.keys
        out_names = self.col_names or [f"mean({c})" for c in self.cols]
        key_col = (
            table[keys[0]]
            if len(keys) == 1
            else np.array([tuple(table[k][i] for k in keys) for i in range(table.num_rows)],
                          dtype=object)
        )
        groups: Dict[Any, List[int]] = {}
        for i, k in enumerate(key_col):
            kk = k.item() if isinstance(k, np.generic) else k
            groups.setdefault(kk, []).append(i)
        means: Dict[str, Dict[Any, Any]] = {c: {} for c in self.cols}
        for c in self.cols:
            col = table[c]
            for k, idxs in groups.items():
                vals = [np.asarray(col[i], dtype=np.float64) for i in idxs]
                means[c][k] = np.mean(np.stack(vals), axis=0)
        if self.collapse_group:
            group_keys = list(groups.keys())
            cols: Dict[str, Any] = {}
            for j, k in enumerate(keys):
                cols[k] = [gk if len(keys) == 1 else gk[j] for gk in group_keys]
            for c, o in zip(self.cols, out_names):
                vals = [means[c][gk] for gk in group_keys]
                cols[o] = vals if np.asarray(vals[0]).ndim else np.asarray(vals)
            return Table(cols)
        out = table
        for c, o in zip(self.cols, out_names):
            vals = [means[c][k.item() if isinstance(k, np.generic) else k] for k in key_col]
            out = out.with_column(o, vals if np.asarray(vals[0]).ndim else np.asarray(vals))
        return out


@register_stage
class ClassBalancer(Estimator):
    """Adds an inverse-frequency weight column: weight = max_count / count.
    Reference: stages/ClassBalancer.scala:25."""

    input_col = Param("label column", default="label")
    output_col = Param("weight column", default="weight")
    broadcast_join = Param("kept for API parity", default=True,
                           converter=TypeConverters.to_bool)

    def _fit(self, table: Table) -> "ClassBalancerModel":
        col = table[self.input_col]
        if len(col) == 0:
            raise ValueError("ClassBalancer: cannot fit on an empty table")
        vals, counts = np.unique(np.asarray(col), return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        return ClassBalancerModel(
            input_col=self.input_col,
            output_col=self.output_col,
            weights={v.item() if isinstance(v, np.generic) else v: float(w)
                     for v, w in zip(vals, weights)},
        )


@register_stage
class ClassBalancerModel(Model):
    input_col = Param("label column", default="label")
    output_col = Param("weight column", default="weight")
    weights = ComplexParam("label -> weight map")

    def _transform(self, table: Table) -> Table:
        w = self.weights
        # unseen labels get NaN, matching the reference's left-join nulls
        out = np.array([w.get(v.item() if isinstance(v, np.generic) else v, np.nan)
                        for v in table[self.input_col]])
        return table.with_column(self.output_col, out)


@register_stage
class SummarizeData(Transformer):
    """Counts / missing / quantile / basic-stat summary per column.
    Reference: stages/SummarizeData.scala:101."""

    counts = Param("include counts", default=True, converter=TypeConverters.to_bool)
    basic = Param("include basic stats", default=True, converter=TypeConverters.to_bool)
    percentiles = Param("include percentiles", default=True, converter=TypeConverters.to_bool)

    def _transform(self, table: Table) -> Table:
        records = []
        for name in table.column_names:
            col = table.columns[name]
            rec: Dict[str, Any] = {"Feature": name}
            is_num = col.dtype.kind in "ifub"
            vals = col.astype(np.float64) if is_num else None
            if self.counts:
                rec["Count"] = float(table.num_rows)
                if is_num:
                    rec["Unique Value Count"] = float(len(np.unique(col)))
                    rec["Missing Value Count"] = float(np.isnan(vals).sum())
                else:
                    rec["Unique Value Count"] = float(len(set(col.tolist())))
                    rec["Missing Value Count"] = float(sum(v is None for v in col))
            if self.basic:
                if is_num and len(vals):
                    rec.update(
                        Min=float(np.nanmin(vals)), Max=float(np.nanmax(vals)),
                        Mean=float(np.nanmean(vals)), Variance=float(np.nanvar(vals, ddof=1))
                        if len(vals) > 1 else 0.0,
                    )
                else:
                    rec.update(Min=np.nan, Max=np.nan, Mean=np.nan, Variance=np.nan)
            if self.percentiles:
                for q, label in [(0.005, "P0.5"), (0.01, "P1"), (0.05, "P5"), (0.25, "P25"),
                                 (0.5, "Median"), (0.75, "P75"), (0.95, "P95"), (0.99, "P99"),
                                 (0.995, "P99.5")]:
                    rec[label] = float(np.nanquantile(vals, q)) if is_num and len(vals) else np.nan
            records.append(rec)
        return Table.from_records(records)


@register_stage
class Timer(Estimator):
    """Wraps a stage and records fit/transform wall time.
    Reference: stages/Timer.scala:55."""

    stage = ComplexParam("wrapped stage")
    log_to_logger = Param("also log", default=True, converter=TypeConverters.to_bool)

    def _fit(self, table: Table) -> "TimerModel":
        inner = self.stage
        t0 = time.perf_counter()
        fitted = inner.fit(table) if isinstance(inner, Estimator) else inner
        fit_time = time.perf_counter() - t0
        return TimerModel(stage=fitted).set(last_fit_time=fit_time)


@register_stage
class TimerModel(Model):
    stage = ComplexParam("wrapped fitted stage")
    last_fit_time = Param("seconds", default=0.0, converter=TypeConverters.to_float)
    last_transform_time = Param("seconds", default=0.0, converter=TypeConverters.to_float)

    def _transform(self, table: Table) -> Table:
        t0 = time.perf_counter()
        out = self.stage.transform(table)
        self.set(last_transform_time=time.perf_counter() - t0)
        return out


@register_stage
class StratifiedRepartition(Transformer):
    """Reassign rows to `n` partitions so every partition sees every label —
    needed by distributed GBDT multiclass (each shard must observe all
    classes).  Emits a `__partition__` column + meta hint.
    Reference: stages/StratifiedRepartition.scala:31."""

    label_col = Param("label column", default="label")
    n = Param("number of partitions", default=None, converter=TypeConverters.to_int)
    mode = Param("equal|original|mixed", default="equal")
    seed = Param("shuffle seed for mixed mode", default=0, converter=TypeConverters.to_int)

    def _transform(self, table: Table) -> Table:
        from ..utils.cluster import get_num_shards

        n = self.n or get_num_shards()
        part = np.zeros(table.num_rows, dtype=np.int32)
        if self.mode == "equal":
            # every partition gets an equal share of every class
            for _, idxs in table.group_indices(self.label_col).items():
                part[idxs] = np.arange(len(idxs)) % n
        elif self.mode == "original":
            # preserve the incoming class distribution per partition
            part = np.arange(table.num_rows, dtype=np.int32) % n
        elif self.mode == "mixed":
            # equal shares, shuffled within each class
            rng = np.random.default_rng(self.seed)
            for _, idxs in table.group_indices(self.label_col).items():
                part[idxs] = rng.permutation(len(idxs)) % n
        else:
            raise ValueError(f"StratifiedRepartition: unknown mode {self.mode!r}")
        out = table.with_column("__partition__", part)
        return out.with_meta("__partitioning__", {"num_partitions": n})


class _ConsolidationRound:
    __slots__ = ("parts", "last_arrival")

    def __init__(self, table, now):
        self.parts = [table]
        self.last_arrival = now


class Consolidator:
    """Election + funnel shared by concurrent transform callers.

    Reference: stages/PartitionConsolidator.scala:51-137 Consolidator — the
    first caller opens a round and is 'chosen'; every caller arriving while
    the round is open deposits its rows INTO the round (atomically, under
    the round lock) and returns empty; the chosen caller closes the round
    once no new deposit has arrived for a grace period and emits everything.
    Because deposit and close both hold the lock, a straggler either lands
    in the round it observed or opens a fresh round it owns — rows can
    never be left behind in a shared buffer after the owner has returned.
    """

    def __init__(self, grace_period_s: float = 1.0, poll_s: float = 0.01):
        import threading

        self.grace_period_s = float(grace_period_s)
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._round: Optional[_ConsolidationRound] = None

    def register_and_receive(self, table: Table) -> Table:
        import time

        with self._lock:
            if self._round is None:
                self._round = rnd = _ConsolidationRound(table, time.monotonic())
                chosen = True
            else:
                self._round.parts.append(table)
                self._round.last_arrival = time.monotonic()
                chosen = False
        if not chosen:
            return table.take(np.empty(0, np.int64))
        # chosen: wait until the round has been quiet for the grace period
        # (the reference's gracePeriod sleep, PartitionConsolidator.scala:76),
        # then close it atomically
        while True:
            with self._lock:
                quiet = time.monotonic() - rnd.last_arrival
                if quiet >= self.grace_period_s:
                    parts = rnd.parts
                    self._round = None
                    break
            time.sleep(min(self.poll_s, self.grace_period_s))
        return Table.concat(parts)


@register_stage
class PartitionConsolidator(Transformer):
    """Funnel all concurrently-transforming data through one elected caller
    per process so a rate-limited per-host resource (one HTTP client, one
    metered API) is driven single-file.

    Reference: stages/PartitionConsolidator.scala:22-49 — 1-of-N Spark
    partitions per JVM is elected via a SharedSingleton Consolidator; here
    the callers are concurrent transform invocations sharing the
    process-wide Consolidator keyed by stage uid.
    """

    grace_period_ms = Param("quiet time before the chosen caller closes its "
                            "round; every round (including a lone caller) "
                            "pays this wait once.  Default 250ms trades the "
                            "reference's 1s gracePeriod "
                            "(PartitionConsolidator.scala:76) for per-batch "
                            "latency; raise it when concurrent callers can "
                            "arrive far apart", default=250,
                            converter=TypeConverters.to_int)

    def _transform(self, table: Table) -> Table:
        # key includes the grace so stage.set(grace_period_ms=...) after a
        # first transform is honored (same rule as get_shared_client)
        grace = int(self.grace_period_ms)
        consolidator = shared_singleton(
            ("PartitionConsolidator", self.uid, grace),
            lambda: Consolidator(grace_period_s=grace / 1000.0),
        )
        return consolidator.register_and_receive(table)
