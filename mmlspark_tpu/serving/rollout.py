"""Fleet control plane, control half: versioned canary rollouts gated on
the perf-band engine.

A :class:`RolloutController` drives one canary at a time through a small
state machine::

    idle --begin()--> canary --step()--> promoted
                        |                   (canary weight -> 1.0, old
                        |                    replicas rolling-drained)
                        +-----step()--> rolled_back
                                            (canary weight -> 0, canary
                                             replicas drained + stopped)

While in ``canary`` the gateway splits traffic by version weight (e.g.
95/5).  Every :meth:`step` re-reads the gateway's per-version rolling
stats (in-window request/error counts, latency percentiles over the
forward window) and diffs canary vs baseline with the SAME
direction+tolerance-band logic the repo's bench regression gate uses
(tools/perf_gate.py ``compare``): a metric regresses only when it is
worse by more than ``abs(base)*rel + floor``.  The verdict is
hysteresis-free by design — one bad evaluation rolls back — because a
canary sample is cheap to retake and a bad canary is expensive to keep.

Rollback triggers (ROLLOUT_METRICS bands):

* ``latency_p50`` / ``latency_p95`` — canary slower than baseline by
  >50% relative + 10ms absolute floor (floor absorbs scheduler jitter
  at sub-ms service times).
* ``error_rate`` — canary error rate above baseline + 2 points absolute
  (floor-dominated: baseline error rates are ~0, so a relative band
  alone would trip on a single flake).

Promotion requires ``min_requests`` canary samples with NO metric
outside its band; the old version's replicas are then rolling-drained:
``begin_drain`` (in-process) or ``POST /admin/drain`` (remote), wait for
``drained`` (bounded by ``drain_timeout_s``), then stop — so no accepted
request is dropped during the roll.

Operator story: docs/serving.md.  Data plane: serving/fleet.py.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..core import telemetry
from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData
from ..utils.sync import make_lock
from .fleet import FleetGateway, Replica

__all__ = ["RolloutController", "ROLLOUT_METRICS", "drain_and_stop"]

# metric -> (direction, relative tolerance, absolute floor) — the
# perf_gate band shape (tools/perf_gate.py GATE_METRICS).
ROLLOUT_METRICS: Dict[str, Tuple[str, float, float]] = {
    "latency_p50": ("lower", 0.50, 0.010),
    "latency_p95": ("lower", 0.50, 0.010),
    "error_rate": ("lower", 0.0, 0.02),
}


def _band_compare(fresh: Dict[str, Any], base: Dict[str, Any],
                  metrics: Dict[str, Tuple[str, float, float]],
                  ) -> List[Dict[str, Any]]:
    """tools/perf_gate.compare with the rollout band table; falls back
    to an inline copy of the band rule when tools/ is not importable
    (installed-package layouts)."""
    try:
        from tools.perf_gate import compare
        rows, _ = compare(fresh, base, metrics=metrics)
        return rows
    except ImportError:
        rows = []
        for name, (direction, rel, floor) in metrics.items():
            f, b = fresh.get(name), base.get(name)
            if not isinstance(f, (int, float)) or \
                    not isinstance(b, (int, float)):
                continue
            band = abs(b) * rel + floor
            worse_by = (b - f) if direction == "higher" else (f - b)
            rows.append({"metric": name, "direction": direction,
                         "base": b, "fresh": f, "band": band,
                         "delta_pct": ((f - b) / b * 100.0) if b else None,
                         "regressed": worse_by > band})
        return rows


def drain_and_stop(gateway: FleetGateway, rep: Replica,
                   drain_timeout_s: float = 10.0) -> None:
    """Gracefully retire one replica: begin_drain -> wait drained
    (bounded) -> stop.  In-process via the ServingServer handle, or
    remotely via ``POST /admin/drain`` + ``/health`` polling (a remote
    replica's process is stopped by its owner; the gateway just stops
    routing to it).  The drain mark goes through the gateway so it is
    sticky: a health probe racing this drain (remote /health still says
    draining=false) must not flip the replica back to routable.

    Shared by RolloutController (promote/rollback retirements) and
    AutoscaleController (scale-down) — one drain discipline, no
    accepted request dropped by either control loop."""
    gateway.begin_drain(rep.key)
    deadline = time.monotonic() + drain_timeout_s
    if rep.server is not None:
        rep.server.server.begin_drain()
        while (time.monotonic() < deadline
               and not rep.server.server.drained()):
            time.sleep(0.01)
        rep.server.stop(drain=False)  # already drained above
        return
    base = f"http://{rep.info.host}:{rep.info.port}"
    try:
        send_request(HTTPRequestData(
            url=base + "/admin/drain",
            headers={"Content-Type": "application/json"},
            entity=b"{}"), timeout=5.0)
        while time.monotonic() < deadline:
            resp = send_request(HTTPRequestData(
                url=base + "/health", method="GET"), timeout=2.0)
            if resp.ok and resp.json().get("drained"):
                break
            time.sleep(0.05)
    except Exception:  # noqa: BLE001 — replica died mid-drain: done
        pass


class RolloutController:
    """Drive a canary split on a :class:`FleetGateway` and auto-promote
    or auto-roll-back on the perf-band verdict.

    ``step()`` is the unit of control: call it from a cron, an operator
    loop, or ``run(poll_s)`` (a daemon thread stepping until the rollout
    resolves).  Tests call it directly for determinism.
    """

    def __init__(self, gateway: FleetGateway,
                 canary_weight: float = 0.05,
                 min_requests: int = 20,
                 metrics: Optional[Dict[str, Tuple[str, float, float]]] = None,
                 drain_timeout_s: float = 10.0):
        self.gateway = gateway
        self.canary_weight = float(canary_weight)
        self.min_requests = int(min_requests)
        self.metrics = dict(metrics or ROLLOUT_METRICS)
        self.drain_timeout_s = float(drain_timeout_s)
        self.state = "idle"
        self.baseline_version: Optional[str] = None
        self.canary_version: Optional[str] = None
        self._baseline_mark: Dict[str, Dict[str, int]] = {}
        self.last_rows: List[Dict[str, Any]] = []
        self.last_verdict: Optional[str] = None
        self.history: List[dict] = []
        self._lock = make_lock("serving.rollout.manager")
        gateway.rollout = self

    # ---- state machine -------------------------------------------------
    def begin(self, canary_version: str,
              baseline_version: Optional[str] = None,
              weight: Optional[float] = None) -> None:
        """Open the canary split.  The canary replicas must already be in
        the gateway pool (``add_server`` / ``add_replica`` / registry
        sync) under `canary_version`."""
        with self._lock:
            if self.state == "canary":
                raise RuntimeError(
                    f"rollout already in flight ({self.canary_version})")
            versions = {r.version for r in self.gateway.replicas()}
            if canary_version not in versions:
                raise ValueError(f"no replicas registered for canary "
                                 f"version {canary_version!r}")
            if baseline_version is None:
                others = sorted(versions - {canary_version})
                if len(others) != 1:
                    raise ValueError(
                        f"ambiguous baseline among {sorted(versions)}; "
                        f"pass baseline_version")
                baseline_version = others[0]
            w = self.canary_weight if weight is None else float(weight)
            self.baseline_version = baseline_version
            self.canary_version = canary_version
            # in-window deltas: mark both versions' counters at open
            self._baseline_mark = {
                v: {"n": s["requests"], "errors": s["errors"]}
                for v, s in ((v, self.gateway.version_stats(v))
                             for v in (baseline_version, canary_version))}
            self.gateway.set_version_weight(baseline_version, 1.0 - w)
            self.gateway.set_version_weight(canary_version, w)
            self.state = "canary"
            self.last_rows, self.last_verdict = [], None
            self.history.append({"event": "begin",
                                 "canary": canary_version,
                                 "baseline": baseline_version,
                                 "weight": w})

    def _window_stats(self, version: str) -> Dict[str, Any]:
        st = self.gateway.version_stats(version)
        mark = self._baseline_mark.get(version, {"n": 0, "errors": 0})
        n = st["requests"] - mark["n"]
        errors = st["errors"] - mark["errors"]
        return {
            "requests": n,
            "errors": errors,
            "error_rate": (errors / n) if n > 0 else 0.0,
            "latency_p50": st["latency_p50"],
            "latency_p95": st["latency_p95"],
        }

    def evaluate(self) -> str:
        """One perf-band verdict: 'warming' (not enough canary samples),
        'ok', or 'regressed'.  Pure read — no weight changes."""
        if self.state != "canary":
            return self.state
        canary = self._window_stats(self.canary_version)
        base = self._window_stats(self.baseline_version)
        if canary["requests"] < self.min_requests or base["requests"] < 1:
            self.last_verdict = "warming"
            return "warming"
        self.last_rows = _band_compare(canary, base, self.metrics)
        verdict = ("regressed"
                   if any(r["regressed"] for r in self.last_rows)
                   else "ok")
        self.last_verdict = verdict
        return verdict

    def step(self) -> str:
        """Evaluate and act: promote on 'ok', roll back on 'regressed'.
        Returns the controller state after the step."""
        verdict = self.evaluate()
        if verdict == "ok":
            self.promote()
        elif verdict == "regressed":
            self.rollback()
        return self.state

    def promote(self) -> None:
        """Canary takes all traffic; the old version's replicas are
        rolling-drained (no accepted request dropped) and removed."""
        with self._lock:
            if self.state != "canary":
                return
            old, new = self.baseline_version, self.canary_version
            self.gateway.set_version_weight(new, 1.0)
            self.gateway.set_version_weight(old, 0.0)
            self.state = "promoted"
            self.history.append({"event": "promote", "version": new,
                                 "rows": self.last_rows})
        telemetry.incr("serving.fleet.promote")
        for rep in self.gateway.replicas(version=old):
            self._drain_and_stop(rep)
            self.gateway.remove_replica(rep.key)

    def rollback(self) -> None:
        """Baseline takes all traffic back; canary replicas are drained,
        stopped, and removed from the pool."""
        with self._lock:
            if self.state != "canary":
                return
            old, new = self.baseline_version, self.canary_version
            self.gateway.set_version_weight(old, 1.0)
            self.gateway.set_version_weight(new, 0.0)
            self.state = "rolled_back"
            self.history.append({"event": "rollback", "version": new,
                                 "rows": self.last_rows})
        telemetry.incr("serving.fleet.rollback")
        for rep in self.gateway.replicas(version=new):
            self._drain_and_stop(rep)
            self.gateway.remove_replica(rep.key)

    # ---- rolling drain -------------------------------------------------
    def _drain_and_stop(self, rep: Replica) -> None:
        drain_and_stop(self.gateway, rep, self.drain_timeout_s)

    # ---- optional background stepping ---------------------------------
    def run(self, poll_s: float = 1.0) -> threading.Thread:
        """Step on an interval until the rollout resolves."""
        def _loop():
            while self.state == "canary":
                time.sleep(poll_s)
                self.step()
        t = threading.Thread(target=_loop, daemon=True,
                             name="fleet-rollout")
        t.start()
        return t

    # ---- observability -------------------------------------------------
    def describe(self) -> dict:
        return {
            "state": self.state,
            "baseline_version": self.baseline_version,
            "canary_version": self.canary_version,
            "canary_weight": self.canary_weight,
            "min_requests": self.min_requests,
            "last_verdict": self.last_verdict,
            "last_rows": self.last_rows,
            "history": self.history,
        }
