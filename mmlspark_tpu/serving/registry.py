"""Service registry: workers report endpoints to a coordinator service.

Reference: HTTPSourceV2.scala DriverServiceUtils (:133-194) — the driver
hosts a registry every worker POSTs its ServiceInfo{host,port,...} to, and
HTTPSourceStateHolder.serviceInfoJson(name) exposes discovery (:409-416).

In a multi-host jax job the registry runs on the coordinator (process 0);
workers register their per-host serving endpoints over DCN.
"""
from __future__ import annotations

import json
import threading
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData
from .server import ServiceInfo

__all__ = ["ServiceRegistry", "register_service", "list_services"]


class ServiceRegistry:
    """Tiny registry server: POST /register, GET /services."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._services: Dict[str, List[dict]] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                if self.path.rstrip("/") != "/register":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                info = json.loads(self.rfile.read(length))
                with outer._lock:
                    outer._services.setdefault(info["name"], []).append(info)
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def do_GET(self):
                if not self.path.rstrip("/").startswith("/services"):
                    self.send_error(404)
                    return
                name = self.path.rstrip("/").split("/")[-1]
                with outer._lock:
                    if name and name != "services":
                        body = json.dumps(
                            outer._services.get(name, [])
                        ).encode()
                    else:
                        body = json.dumps(outer._services).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="svc-registry"
        )

    @property
    def url(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> str:
        self._thread.start()
        return self.url

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def services(self, name: Optional[str] = None):
        with self._lock:
            if name is not None:
                return list(self._services.get(name, []))
            return {k: list(v) for k, v in self._services.items()}


def register_service(registry_url: str, info: ServiceInfo) -> bool:
    resp = send_request(HTTPRequestData(
        url=registry_url.rstrip("/") + "/register",
        headers={"Content-Type": "application/json"},
        entity=json.dumps(asdict(info)).encode(),
    ), timeout=10.0)
    return resp.ok


def list_services(registry_url: str, name: str) -> List[dict]:
    resp = send_request(HTTPRequestData(
        url=registry_url.rstrip("/") + f"/services/{name}", method="GET",
    ), timeout=10.0)
    return resp.json() if resp.ok else []
