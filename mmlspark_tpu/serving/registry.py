"""Service registry: workers report endpoints to a coordinator service.

Reference: HTTPSourceV2.scala DriverServiceUtils (:133-194) — the driver
hosts a registry every worker POSTs its ServiceInfo{host,port,...} to, and
HTTPSourceStateHolder.serviceInfoJson(name) exposes discovery (:409-416).

In a multi-host jax job the registry runs on the coordinator (process 0);
workers register their per-host serving endpoints over DCN.

Entries are keyed by ``(name, host, port)``: re-registration is a
heartbeat (it refreshes ``last_seen``, never duplicates), entries older
than ``ttl_s`` are expired on every read so a dead worker stops being
discoverable within one TTL, and ``POST /deregister`` removes an entry
immediately (the graceful half — the gateway uses it when it drains a
replica out of a fleet, serving/fleet.py).

TTL caveat: expiry is evaluated on READ only — nothing here pushes a
death notification, so a replica that dies between a gateway's registry
syncs remains listed (and, on the gateway, routable) until the next
sync/probe notices.  The gateway-side close for that gap is the
federated telemetry puller (serving/fleet.py FleetTelemetry): a failed
``/metrics.json`` pull marks the replica unhealthy immediately through
the probe/breaker path.  ``prune()`` is the explicit server-side sweep
for operators/tests that want expiry without a read.
"""
from __future__ import annotations

import json
import threading
import time
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..io.http.clients import send_request
from ..io.http.schema import HTTPRequestData
from .server import ServiceInfo

__all__ = ["ServiceRegistry", "register_service", "deregister_service",
           "list_services"]


class ServiceRegistry:
    """Tiny registry server: POST /register, POST /deregister,
    GET /services[/name].

    `ttl_s` is the heartbeat contract: a worker that has not re-POSTed
    /register within `ttl_s` seconds is expired on the next read
    (`ttl_s=None` disables expiry).  The clock is injectable so tests
    can expire entries without sleeping.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ttl_s: Optional[float] = 30.0, clock=time.monotonic):
        # (name, host, port) -> info dict (+ "_last_seen" stamp)
        self._services: Dict[Tuple[str, str, int], dict] = {}
        self._lock = threading.Lock()
        self.ttl_s = None if ttl_s is None else float(ttl_s)
        self._clock = clock
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                path = self.path.rstrip("/")
                if path not in ("/register", "/deregister"):
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    info = json.loads(self.rfile.read(length))
                    key = (str(info["name"]), str(info["host"]),
                           int(info["port"]))
                except (ValueError, KeyError, TypeError):
                    self.send_error(400, "need JSON with name/host/port")
                    return
                if path == "/register":
                    outer._put(key, info)
                else:
                    outer._remove(key)
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"ok")

            def do_GET(self):
                if not self.path.rstrip("/").startswith("/services"):
                    self.send_error(404)
                    return
                name = self.path.rstrip("/").split("/")[-1]
                if name and name != "services":
                    body = json.dumps(outer.services(name)).encode()
                else:
                    body = json.dumps(outer.services()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="svc-registry"
        )

    # ---- store ---------------------------------------------------------
    def _put(self, key: Tuple[str, str, int], info: dict):
        with self._lock:
            entry = dict(info)
            entry["_last_seen"] = self._clock()
            self._services[key] = entry

    def _remove(self, key: Tuple[str, str, int]):
        with self._lock:
            self._services.pop(key, None)

    def _prune_locked(self):
        if self.ttl_s is None:
            return
        cutoff = self._clock() - self.ttl_s
        for k in [k for k, v in self._services.items()
                  if v.get("_last_seen", 0.0) < cutoff]:
            del self._services[k]

    def prune(self) -> int:
        """Explicit TTL sweep (the read path runs this implicitly).
        Returns the number of entries remaining."""
        with self._lock:
            self._prune_locked()
            return len(self._services)

    @staticmethod
    def _public(entry: dict) -> dict:
        return {k: v for k, v in entry.items() if not k.startswith("_")}

    # ---- server lifecycle ---------------------------------------------
    @property
    def url(self) -> str:
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def start(self) -> str:
        self._thread.start()
        return self.url

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    # ---- read side (TTL expiry happens here) --------------------------
    def services(self, name: Optional[str] = None):
        with self._lock:
            self._prune_locked()
            if name is not None:
                return [self._public(v) for (n, _h, _p), v
                        in self._services.items() if n == name]
            out: Dict[str, List[dict]] = {}
            for (n, _h, _p), v in self._services.items():
                out.setdefault(n, []).append(self._public(v))
            return out


def register_service(registry_url: str, info: ServiceInfo) -> bool:
    """Register (or heartbeat) one endpoint.  Idempotent: the registry
    keys on (name, host, port), so re-POSTing refreshes `last_seen`."""
    resp = send_request(HTTPRequestData(
        url=registry_url.rstrip("/") + "/register",
        headers={"Content-Type": "application/json"},
        entity=json.dumps(asdict(info)).encode(),
    ), timeout=10.0)
    return resp.ok


def deregister_service(registry_url: str, info: ServiceInfo) -> bool:
    """Remove one endpoint immediately (graceful shutdown / drain)."""
    resp = send_request(HTTPRequestData(
        url=registry_url.rstrip("/") + "/deregister",
        headers={"Content-Type": "application/json"},
        entity=json.dumps(asdict(info)).encode(),
    ), timeout=10.0)
    return resp.ok


def list_services(registry_url: str, name: str) -> List[dict]:
    resp = send_request(HTTPRequestData(
        url=registry_url.rstrip("/") + f"/services/{name}", method="GET",
    ), timeout=10.0)
    return resp.json() if resp.ok else []
