"""Streaming DSL: the `readStream.server ... makeReply ... writeStream`
surface, plus the distributed multi-server variant.

Reference: io/IOImplicits.scala:22-199 —

    spark.readStream.server(host, port, api)       (HTTPSource, microbatch)
         .distributedServer(...)                   (DistributedHTTPSource)
         .continuousServer(...)                    (HTTPSourceV2 continuous)
    df.parseRequest(apiName, schema)
      .mlTransform(model)
      .makeReply(col)
      .writeStream.server(...).start()

TPU-native rendering: the source/query/sink triple builds one (or N)
`ServingServer`s, so the fluent chain configures what `start()` launches:

    query = (read_stream()
             .continuous_server(host, port, name="scoring", path="/score")
             .parse_request(schema=["x"])
             .transform(model)             # any Transformer / Table fn
             .make_reply("prediction")
             .start())
    query.service_info.url -> POST here
    query.stop()

`distributed_server(replicas=k)` starts k per-process servers sharing the
model — the per-JVM shared-server round robin of
DistributedHTTPSource.scala:39-426 — and registers every replica with an
optional `ServiceRegistry` for discovery (DriverServiceUtils :133-194).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

from ..core.pipeline import LambdaTransformer, Transformer
from .registry import register_service
from .server import ServiceInfo, ServingServer

__all__ = ["read_stream", "StreamReader", "StreamingQuery",
           "DistributedServingServer"]


class StreamingQuery:
    """A started serving pipeline (the StreamingQuery analog)."""

    def __init__(self, servers: List[ServingServer], on_stop=()):
        self._servers = servers
        self._on_stop = list(on_stop)

    @property
    def service_info(self) -> ServiceInfo:
        return self._servers[0].service_info

    @property
    def service_infos(self) -> List[ServiceInfo]:
        return [s.service_info for s in self._servers]

    @property
    def stats(self) -> dict:
        agg: dict = {}
        for s in self._servers:
            for k, v in s.stats.items():
                agg[k] = agg.get(k, 0) + v
        return agg

    def is_active(self) -> bool:
        return any(s._running.is_set() for s in self._servers)

    def stop(self) -> None:
        for s in self._servers:
            s.stop()
        for fn in self._on_stop:
            fn()


class StreamReader:
    """Fluent builder; every method returns self until `start()`."""

    def __init__(self):
        self._host = "127.0.0.1"
        self._port = 0
        self._name = "serving"
        self._path = "/"
        self._mode = "continuous"
        self._replicas = 1
        self._registry_url: Optional[str] = None
        self._schema: Optional[List[str]] = None
        self._model: Optional[Transformer] = None
        self._reply_col: Optional[str] = None
        self._max_batch = 64
        self._batch_timeout_ms = 10.0
        self._trigger_interval_ms = 20.0
        self._journal_path: Optional[str] = None
        self._stream_fn = None
        self._gen_cfg = None
        self._stream_workers = 8

    # ---- sources (IOImplicits server/distributedServer/continuousServer)
    def server(self, host: str = "127.0.0.1", port: int = 0,
               name: str = "serving", path: str = "/") -> "StreamReader":
        """Head-node microbatch server (HTTPSource V1 semantics)."""
        self._host, self._port, self._name, self._path = host, port, name, path
        self._mode = "microbatch"
        return self

    def continuous_server(self, host: str = "127.0.0.1", port: int = 0,
                          name: str = "serving", path: str = "/"
                          ) -> "StreamReader":
        """Continuous-batching server (HTTPSourceV2 continuous mode)."""
        self._host, self._port, self._name, self._path = host, port, name, path
        self._mode = "continuous"
        return self

    def distributed_server(self, host: str = "127.0.0.1", port: int = 0,
                           name: str = "serving", path: str = "/",
                           replicas: int = 2,
                           registry_url: Optional[str] = None
                           ) -> "StreamReader":
        """N per-process servers sharing the model (DistributedHTTPSource's
        per-JVM shared servers); replicas register with the registry for
        discovery.  A fixed port only makes sense for one replica."""
        if replicas > 1 and port != 0:
            raise ValueError("distributed_server with replicas > 1 needs "
                             "port=0 (each replica binds its own)")
        self._host, self._port, self._name, self._path = host, port, name, path
        self._mode = "continuous"
        self._replicas = int(replicas)
        self._registry_url = registry_url
        return self

    # ---- query ---------------------------------------------------------
    def parse_request(self, schema: Optional[Sequence[str]] = None
                      ) -> "StreamReader":
        self._schema = list(schema) if schema is not None else None
        return self

    def transform(self, model: Union[Transformer, Callable]) -> "StreamReader":
        if not isinstance(model, Transformer):
            model = LambdaTransformer(model)
        self._model = model
        return self

    def make_reply(self, reply_col: str) -> "StreamReader":
        self._reply_col = reply_col
        return self

    def stream_reply(self, fn) -> "StreamReader":
        """Streaming sink (replaces transform+make_reply): `fn(row) ->
        iterable of str/bytes` chunks, flushed to the client as produced —
        the token-by-token generation shape.  At-most-once delivery."""
        self._stream_fn = fn
        self._gen_cfg = None   # the sinks are mutually exclusive
        return self

    def generate_stream(self, model, variables, tokenizer=None,
                        max_new_tokens: int = 32, max_slots: int = 8,
                        kv_cache_dtype=None,
                        paged: bool = False, page_size: int = 64,
                        num_pages=None,
                        draft_model=None, draft_variables=None,
                        gamma: int = 4) -> "StreamReader":
        """The whole LM endpoint in one call: a ContinuousBatcher owns
        the decode (concurrent clients share one slotted device step) and
        stops with the query.  With a `tokenizer` (BPETokenizerModel),
        requests post {"prompt": "<text>"} and stream decoded text
        chunks; without one, {"prompt": [ids...]} streams token ids.
        The batcher is built PER start() call, so a builder can start
        several independent queries.  `paged=True` serves from page
        pools (pay-per-page KV HBM); `draft_model`/`draft_variables`
        turn on speculative continuous batching (up to gamma+1 tokens
        per slot per target forward, outputs exactly the target's)."""
        self._gen_cfg = dict(model=model, variables=variables,
                             tokenizer=tokenizer,
                             max_new_tokens=int(max_new_tokens),
                             max_slots=int(max_slots),
                             kv_cache_dtype=kv_cache_dtype,
                             paged=bool(paged), page_size=int(page_size),
                             num_pages=num_pages,
                             draft_model=draft_model,
                             draft_variables=draft_variables,
                             gamma=int(gamma))
        self._stream_fn = None
        return self

    def options(self, max_batch: Optional[int] = None,
                batch_timeout_ms: Optional[float] = None,
                trigger_interval_ms: Optional[float] = None,
                journal_path: Optional[str] = None,
                stream_workers: Optional[int] = None) -> "StreamReader":
        """journal_path is the `checkpointLocation` analog: accepted
        requests survive process restart (replicas > 1 each get their own
        `<path>-<replica>` file).  stream_workers sizes the stream_reply
        producer pool (concurrent generations per replica)."""
        if max_batch is not None:
            self._max_batch = int(max_batch)
        if batch_timeout_ms is not None:
            self._batch_timeout_ms = float(batch_timeout_ms)
        if trigger_interval_ms is not None:
            self._trigger_interval_ms = float(trigger_interval_ms)
        if journal_path is not None:
            self._journal_path = journal_path
        if stream_workers is not None:
            self._stream_workers = int(stream_workers)
        return self

    # ---- sink ----------------------------------------------------------
    def start(self) -> StreamingQuery:
        if (self._stream_fn is None and self._gen_cfg is None and (
                self._model is None or self._reply_col is None)):
            raise ValueError("streaming query needs .transform(model) and "
                             ".make_reply(col) — or .stream_reply(fn) / "
                             ".generate_stream(...) — before start()")
        batcher = None
        stream_fn = self._stream_fn
        if self._gen_cfg is not None:
            from .batcher import ContinuousBatcher

            cfg = self._gen_cfg
            # generate_stream populates every key; defaults live THERE
            batcher = ContinuousBatcher(
                cfg["model"], cfg["variables"], max_slots=cfg["max_slots"],
                kv_cache_dtype=cfg["kv_cache_dtype"], paged=cfg["paged"],
                page_size=cfg["page_size"], num_pages=cfg["num_pages"],
                draft_model=cfg["draft_model"],
                draft_variables=cfg["draft_variables"], gamma=cfg["gamma"])

            def stream_fn(row, _b=batcher, _c=cfg):
                if _c["tokenizer"] is not None:
                    yield from _b.stream_text(_c["tokenizer"],
                                              str(row["prompt"]),
                                              _c["max_new_tokens"])
                else:
                    for tok in _b.submit([int(t) for t in row["prompt"]],
                                         _c["max_new_tokens"]):
                        yield f"{tok} "

        servers = []
        for r in range(self._replicas):
            srv = ServingServer(
                model=self._model, reply_col=self._reply_col,
                stream_fn=stream_fn,
                stream_workers=self._stream_workers,
                name=self._name if self._replicas == 1
                else f"{self._name}-{r}",
                host=self._host, port=self._port, path=self._path,
                input_schema=self._schema, max_batch=self._max_batch,
                batch_timeout_ms=self._batch_timeout_ms, mode=self._mode,
                trigger_interval_ms=self._trigger_interval_ms,
                journal_path=(None if self._journal_path is None
                              else self._journal_path if self._replicas == 1
                              else f"{self._journal_path}-{r}"))
            info = srv.start()
            if self._registry_url:
                register_service(self._registry_url,
                                 ServiceInfo(self._name, info.host,
                                             info.port, info.path))
            servers.append(srv)
        on_stop = []
        if batcher is not None:
            batcher.start()
            on_stop.append(batcher.stop)
        query = StreamingQuery(servers, on_stop=on_stop)
        query._batcher = batcher   # observability (tests, diagnostics)
        return query


def read_stream() -> StreamReader:
    """Entry point mirroring `spark.readStream` (IOImplicits.scala:22)."""
    return StreamReader()


class DistributedServingServer:
    """Convenience wrapper: N replicas + a registry in one object."""

    def __init__(self, model, reply_col: str, name: str = "serving",
                 path: str = "/", replicas: int = 2, registry=None,
                 **options):
        from .registry import ServiceRegistry

        self._own_registry = registry is None
        self._registry_started = False
        self.registry = registry or ServiceRegistry()
        self._builder = (read_stream()
                         .distributed_server(name=name, path=path,
                                             replicas=replicas)
                         .transform(model)
                         .make_reply(reply_col)
                         .options(**options))
        self.query: Optional[StreamingQuery] = None

    def start(self) -> List[ServiceInfo]:
        if self.query is not None:
            raise RuntimeError("DistributedServingServer already started")
        if self._own_registry:
            self.registry.start()
            self._registry_started = True
        self._builder._registry_url = self.registry.url
        self.query = self._builder.start()
        return self.query.service_infos

    def stop(self):
        if self.query is not None:
            self.query.stop()
            self.query = None
        # shutting down a never-started ThreadingHTTPServer deadlocks
        # (socketserver waits on serve_forever's event): only stop what ran
        if self._own_registry and self._registry_started:
            self.registry.stop()
            self._registry_started = False
