"""Fleet control plane, data half: a health-checked HTTP gateway routing
over a pool of worker replicas.

Reference lineage: the paper's driver-side registry (HTTPSourceV2
DriverServiceUtils) sketches discovery; Clipper-style model-serving
frontends sketch the rest — a thin routing tier in front of N identical
model replicas, with load-aware balancing, passive failure ejection, and
active health-probe reinstatement.  The single-replica machinery this
fronts (drain, deadlines, shedding, journal replay) lives in
serving/server.py; the rollout/canary control half lives in
serving/rollout.py; the operator story is docs/serving.md.

Routing policy
--------------
* Replicas are grouped by ``version``; a version is picked by weight
  (explicit canary splits via :meth:`FleetGateway.set_version_weight`,
  else proportional to the replicas' registered weights).
* Within the version group: **power of two choices** on per-replica
  in-flight counts — sample two distinct replicas, forward to the one
  with fewer requests currently in flight.  P2C gets most of the benefit
  of join-shortest-queue at O(1) cost and without herding on one
  momentarily-idle replica.
* A replica is routable while it is healthy (last probe succeeded), not
  draining, and its circuit is not open.

Deadline rule
-------------
The gateway decrements a client's ``X-Deadline-Ms`` budget by its own
observed elapsed time before every forward (including before a retry),
so the replica sees only the budget that is actually left.  An exhausted
budget is answered 504 at the gateway — never forwarded.

Retries
-------
A transport failure (replica died mid-exchange) or a 503 (shed /
draining) is retried on an ALTERNATE replica, at most ``retries`` times,
only while deadline budget remains, and never after response body bytes
have been relayed — a chunked stream that dies mid-body closes the
client connection rather than replaying a half-delivered stream.
Requests carrying ``X-Idempotent: false`` are never retried.

Ejection / reinstatement
------------------------
Each replica holds a PR-4 :class:`~mmlspark_tpu.io.http.clients.
CircuitBreaker` from the process-shared ``get_breaker`` registry:
consecutive transport failures open the circuit (passive ejection, no
more traffic).  A background prober GETs every replica's ``/health`` on
an interval (fault point ``fleet.health``); a live answer closes the
circuit and reinstates the replica, so a revived process at the same
address rejoins the pool without operator action.

Fleet telemetry plane (PR 15)
-----------------------------
:class:`FleetTelemetry` is the HTTP half of `core/telemetry/fleet.py`:
it pulls every replica's ``/metrics.json`` snapshot (never holding the
gateway routing lock across the wire), merges them exactly, feeds the
SLO burn-rate engine, and exposes ``GET /fleet/metrics`` (Prometheus +
JSON), ``GET /fleet/alerts``, ``GET /fleet/goodput`` (the federated
goodput/straggler view), and a federated ``GET /trace/<id>`` that
stitches one client trace across gateway + replica span stores.  A pull
failure marks the replica unhealthy through the same probe/breaker path
as an active health-probe failure — closing the registry-TTL gap where
a replica that died between registry syncs stayed routable until the
next scrape.  On an alert transitioning to firing, the attached
FlightRecorder dumps an incident bundle under ``incidents/<ts>/``.
"""
from __future__ import annotations

import http.client
import json
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set

from ..core import telemetry
from ..io.http.clients import CircuitBreaker, get_breaker, send_request
from ..io.http.schema import HTTPRequestData
from ..utils.faults import fault_point
from .registry import list_services
from ..utils.sync import make_lock
from .server import ServiceInfo

__all__ = ["Replica", "FleetGateway", "FleetTelemetry"]

# hop-by-hop (and gateway-owned) headers never copied onto the forward
_HOP_HEADERS = frozenset({
    "connection", "keep-alive", "transfer-encoding", "content-length",
    "host", "te", "upgrade", "proxy-connection",
    "x-trace-id", "x-span-id",     # re-injected from the gateway span
    "x-deadline-ms",               # re-stamped with the decremented budget
})

_LAT_WINDOW = 512  # per-version rolling latency window (rollout gating)


class Replica:
    """One routable backend: endpoint + version/weight + live state."""

    def __init__(self, info: ServiceInfo, breaker: CircuitBreaker,
                 server=None, from_registry: bool = False):
        self.info = info
        self.breaker = breaker
        # optional in-process handle (ServingServer) for lifecycle ops
        # (rolling drains in rollout.py); remote replicas use /admin/drain
        self.server = server
        self.from_registry = from_registry
        self.inflight = 0
        self.healthy = True
        self.draining = False
        # sticky local drain intent (set via FleetGateway.begin_drain,
        # under the gateway lock): a remote /health probe answered
        # before the replica processed /admin/drain reports
        # draining=false, and must not flip this replica back to
        # routable mid-drain
        self.drain_requested = False
        self.forwarded = 0
        self.errors = 0

    @property
    def key(self) -> str:
        return f"{self.info.host}:{self.info.port}"

    @property
    def version(self) -> str:
        return self.info.version

    @property
    def weight(self) -> float:
        return float(self.info.weight)

    def routable(self) -> bool:
        return (self.healthy and not self.draining
                and self.breaker.state != "open")

    def describe(self) -> dict:
        return {
            "url": self.info.url,
            "version": self.info.version,
            "weight": self.info.weight,
            "healthy": self.healthy,
            "draining": self.draining,
            "breaker": self.breaker.state,
            "inflight": self.inflight,
            "forwarded": self.forwarded,
            "errors": self.errors,
        }


class FleetGateway:
    """HTTP gateway fronting a replica pool (see module docstring).

    POSTs to `path` are routed/forwarded; admin surface:

    * ``GET /fleet``   — replica table, version weights + stats, rollout
    * ``GET /health``  — the gateway's own liveness
    * ``GET /metrics`` / ``/trace/<id>`` / ``/trace.json`` — the process
      telemetry registry (same handlers as WorkerServer)
    """

    def __init__(self, name: str = "fleet", path: str = "/",
                 host: str = "127.0.0.1", port: int = 0,
                 registry_url: Optional[str] = None,
                 probe_interval_s: float = 0.25,
                 retries: int = 1,
                 breaker_threshold: int = 2,
                 breaker_reset_s: float = 0.5,
                 forward_timeout_s: float = 30.0,
                 rng: Optional[random.Random] = None,
                 telemetry_interval_s: Optional[float] = None,
                 incident_dir: Optional[str] = None,
                 slos=None):
        self.name = name
        self.path = path if path.startswith("/") else "/" + path
        self.registry_url = registry_url
        self.probe_interval_s = float(probe_interval_s)
        self.retries = int(retries)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self.forward_timeout_s = float(forward_timeout_s)
        self._rng = rng or random.Random()
        self._lock = make_lock("serving.fleet.gateway")
        self._replicas: Dict[str, Replica] = {}
        # explicit canary splits (rollout.py); unset versions weigh
        # proportionally to their replicas' registered weights
        self._version_weights: Dict[str, float] = {}
        # per-version rolling stats feeding the rollout gate
        self._vstats: Dict[str, dict] = {}
        self.rollout = None  # RolloutController attaches itself here
        self.autoscale = None  # AutoscaleController attaches itself here
        # the federated telemetry plane: always constructed (the
        # /fleet/* endpoints pull synchronously when stale), background
        # puller thread only when an interval is configured
        self.telemetry_plane = FleetTelemetry(
            self, pull_interval_s=telemetry_interval_s,
            incident_dir=incident_dir, slos=slos)
        self._running = threading.Event()
        self._stop_evt = threading.Event()  # wakes the prober on stop()
        outer = self

        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def do_POST(self):
                if self.path.rstrip("/") != outer.path.rstrip("/"):
                    self.send_error(404)
                    return
                if "chunked" in self.headers.get(
                        "Transfer-Encoding", "").lower():
                    self.send_error(501, "chunked transfer not supported")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                ctx = telemetry.extract_trace(self.headers)
                t0 = time.perf_counter()
                outcome = "error"
                try:
                    with telemetry.span("serving.fleet.request",
                                        parent_ctx=ctx,
                                        endpoint=outer.path) as sp:
                        outcome = outer._route(self, body,
                                               dict(self.headers.items()),
                                               sp)
                        sp.attrs["outcome"] = outcome
                finally:
                    telemetry.histogram(
                        "serving.fleet.request.latency",
                        endpoint=outer.path, outcome=outcome,
                    ).observe(time.perf_counter() - t0)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/fleet":
                    payload = json.dumps(outer.describe()).encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                if path == "/fleet/metrics":
                    merged = outer.telemetry_plane.ensure_fresh()
                    payload = telemetry.render_fleet_prometheus(
                        merged).encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type":
                                 "text/plain; version=0.0.4; charset=utf-8"})
                    return
                if path == "/fleet/metrics.json":
                    merged = outer.telemetry_plane.ensure_fresh()
                    payload = json.dumps(merged, default=repr).encode(
                        "utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                if path == "/fleet/goodput":
                    # the federated goodput view alone (PR 20): per-host
                    # summaries, fleet lost-time table, straggler verdict
                    merged = outer.telemetry_plane.ensure_fresh()
                    gp = merged.get("goodput") or {
                        "hosts": {}, "fleet": None, "straggler": None}
                    payload = json.dumps(gp, default=repr).encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                if path == "/fleet/alerts":
                    outer.telemetry_plane.ensure_fresh()
                    payload = json.dumps({
                        "alerts": outer.telemetry_plane.engine.alerts(),
                    }, default=repr).encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                if path == "/metrics.json":
                    payload = json.dumps(
                        telemetry.export_snapshot(include_spans=False),
                        default=repr).encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                if path == "/health":
                    self._reply(200, b'{"status": "ok"}',
                                {"Content-Type": "application/json"})
                    return
                if path == "/metrics":
                    payload = telemetry.render_prometheus().encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type":
                                 "text/plain; version=0.0.4; charset=utf-8"})
                    return
                if path == "/trace.json":
                    payload = json.dumps(
                        telemetry.render_chrome_trace()).encode("utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                if path.startswith("/trace/"):
                    # federated: fan out to every replica's span store
                    # and stitch the hops under the client's trace id
                    tid = path[len("/trace/"):].strip("/")
                    stitched = outer.telemetry_plane.fetch_trace(tid)
                    if not stitched["spans"]:
                        self._reply(404, b'{"error": "unknown trace id"}',
                                    {"Content-Type": "application/json"})
                        return
                    payload = json.dumps(stitched, default=repr).encode(
                        "utf-8")
                    self._reply(200, payload,
                                {"Content-Type": "application/json"})
                    return
                self.send_error(404)

            def _reply(self, status: int, body: bytes,
                       headers: Dict[str, str]):
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class _Server(ThreadingHTTPServer):
            request_queue_size = 128

        self._httpd = _Server((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"fleet-gw-{name}")
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True, name=f"fleet-probe-{name}")

    # ---- pool management ----------------------------------------------
    @property
    def service_info(self) -> ServiceInfo:
        h, p = self._httpd.server_address[:2]
        return ServiceInfo(self.name, h, p, self.path)

    @property
    def url(self) -> str:
        return self.service_info.url

    def add_replica(self, info: ServiceInfo, server=None,
                    from_registry: bool = False) -> Replica:
        """Register one backend.  Re-adding the same host:port updates
        version/weight in place (a revived process at the same address
        keeps its replica slot, breaker, and stats)."""
        breaker = get_breaker(f"fleet:{self.name}:{info.host}:{info.port}",
                              failure_threshold=self.breaker_threshold,
                              reset_timeout_s=self.breaker_reset_s)
        with self._lock:
            rep = self._replicas.get(f"{info.host}:{info.port}")
            if rep is None:
                rep = Replica(info, breaker, server=server,
                              from_registry=from_registry)
                self._replicas[rep.key] = rep
            else:
                rep.info = info
                if server is not None:
                    rep.server = server
            self._vstats.setdefault(info.version, {
                "n": 0, "errors": 0, "lat": deque(maxlen=_LAT_WINDOW)})
        self._update_gauges()
        return rep

    def add_server(self, server, version: str = "v1",
                   weight: float = 1.0) -> Replica:
        """Convenience: register an in-process ServingServer replica."""
        info = server.service_info
        info.version, info.weight = version, float(weight)
        return self.add_replica(info, server=server)

    def begin_drain(self, key: str) -> Optional[Replica]:
        """Mark a replica draining, stickily: the flag is set under the
        gateway lock and survives health probes until the replica is
        removed (see Replica.drain_requested)."""
        with self._lock:
            rep = self._replicas.get(key)
            if rep is not None:
                rep.drain_requested = True
                rep.draining = True
        return rep

    def remove_replica(self, key: str) -> Optional[Replica]:
        with self._lock:
            rep = self._replicas.pop(key, None)
        self._update_gauges()
        return rep

    def replicas(self, version: Optional[str] = None) -> List[Replica]:
        with self._lock:
            reps = list(self._replicas.values())
        if version is not None:
            reps = [r for r in reps if r.version == version]
        return reps

    def set_version_weight(self, version: str, weight: float) -> None:
        """Pin one version's share of traffic (canary split).  Weights
        are relative across versions; 0 removes a version from routing
        without touching its replicas."""
        with self._lock:
            self._version_weights[version] = float(weight)

    def sync_registry(self, name: Optional[str] = None) -> int:
        """Pull the replica pool from the ServiceRegistry: add newly
        registered endpoints, drop registry-sourced ones the registry no
        longer lists (TTL-expired or deregistered).  Returns pool size."""
        if self.registry_url is None:
            raise ValueError("gateway constructed without registry_url")
        listed = list_services(self.registry_url, name or self.name)
        seen: Set[str] = set()
        for entry in listed:
            info = ServiceInfo(
                name=entry.get("name", self.name), host=entry["host"],
                port=int(entry["port"]), path=entry.get("path", self.path),
                version=entry.get("version", "v1"),
                weight=float(entry.get("weight", 1.0)))
            seen.add(f"{info.host}:{info.port}")
            self.add_replica(info, from_registry=True)
        with self._lock:
            stale = [k for k, r in self._replicas.items()
                     if r.from_registry and k not in seen]
            for k in stale:
                del self._replicas[k]
            n = len(self._replicas)
        self._update_gauges()
        return n

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> ServiceInfo:
        self._running.set()
        if self.registry_url is not None:
            self.sync_registry()
        self._thread.start()
        self._prober.start()
        self.telemetry_plane.start()
        return self.service_info

    def stop(self):
        self._running.clear()
        self._stop_evt.set()
        self.telemetry_plane.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._prober.join(timeout=5)

    # ---- observability -------------------------------------------------
    def _update_gauges(self):
        with self._lock:
            reps = list(self._replicas.values())
        telemetry.gauge("serving.fleet.replicas").set(len(reps))
        telemetry.gauge("serving.fleet.healthy").set(
            sum(1 for r in reps if r.routable()))

    def version_stats(self, version: str) -> dict:
        """Rolling stats for one version (the rollout gate's input):
        request/error counts plus latency percentiles over the last
        `_LAT_WINDOW` forwards."""
        with self._lock:
            st = self._vstats.get(version)
            if st is None:
                return {"requests": 0, "errors": 0, "error_rate": 0.0,
                        "latency_p50": None, "latency_p95": None}
            lat = sorted(st["lat"])
            n, errors = st["n"], st["errors"]

        def pct(q):
            if not lat:
                return None
            return lat[min(len(lat) - 1, int(q * len(lat)))]

        return {"requests": n, "errors": errors,
                "error_rate": (errors / n) if n else 0.0,
                "latency_p50": pct(0.50), "latency_p95": pct(0.95)}

    def describe(self) -> dict:
        with self._lock:
            reps = [r.describe() for r in self._replicas.values()]
            weights = dict(self._version_weights)
            versions = sorted(self._vstats)
        out = {
            "name": self.name,
            "path": self.path,
            "url": self.url,
            "replicas": reps,
            "version_weights": weights,
            "versions": {v: self.version_stats(v) for v in versions},
        }
        if self.rollout is not None:
            out["rollout"] = self.rollout.describe()
        if self.autoscale is not None:
            out["autoscale"] = self.autoscale.describe()
        out["telemetry"] = self.telemetry_plane.describe()
        return out

    # ---- routing -------------------------------------------------------
    def _choose(self, exclude: Set[str]) -> Optional[Replica]:
        with self._lock:
            pool = [r for r in self._replicas.values()
                    if r.key not in exclude and r.routable()]
            if not pool:
                return None
            groups: Dict[str, List[Replica]] = {}
            for r in pool:
                groups.setdefault(r.version, []).append(r)
            versions, weights = [], []
            for v, grp in groups.items():
                w = self._version_weights.get(
                    v, sum(r.weight for r in grp))
                if w > 0:
                    versions.append(v)
                    weights.append(w)
            if not versions:
                # every version pinned to 0: serve SOMETHING rather than
                # hard-fail a misconfigured split
                versions = list(groups)
                weights = [1.0] * len(versions)
            v = self._rng.choices(versions, weights=weights)[0]
            grp = groups[v]
            if len(grp) == 1:
                return grp[0]
            a, b = self._rng.sample(grp, 2)
            return a if a.inflight <= b.inflight else b

    @staticmethod
    def _parse_deadline_ms(headers: Dict[str, str]) -> Optional[float]:
        for k, v in headers.items():
            if k.lower() == "x-deadline-ms":
                try:
                    return float(v)
                except ValueError:
                    return None
        return None

    @staticmethod
    def _idempotent(headers: Dict[str, str]) -> bool:
        for k, v in headers.items():
            if k.lower() == "x-idempotent":
                return str(v).strip().lower() not in ("false", "0", "no")
        return True

    def _route(self, handler, body: bytes, headers: Dict[str, str],
               sp) -> str:
        """Pick a replica, forward, retry on an alternate within budget.
        Returns the outcome label for the request-latency histogram."""
        t_accept = time.monotonic()
        budget_ms = self._parse_deadline_ms(headers)
        retriable = self._idempotent(headers)
        tried: Set[str] = set()
        attempts = 0
        while True:
            if budget_ms is not None:
                remaining_ms = budget_ms - (
                    time.monotonic() - t_accept) * 1000.0
                if remaining_ms <= 0.0:
                    telemetry.incr("serving.fleet.deadline_expired")
                    self._reply_json(handler, 504, {
                        "error": "deadline exceeded at gateway"})
                    return "timeout"
            else:
                remaining_ms = None
            rep = self._choose(tried)
            if rep is None:
                telemetry.incr("serving.fleet.no_replica")
                self._reply_json(handler, 503, {
                    "error": "no routable replica"},
                    extra={"Retry-After": "1"})
                return "shed"
            tried.add(rep.key)
            sp.attrs["replica"] = rep.key
            sp.attrs["version"] = rep.version
            status, relayed, saved = self._attempt(
                handler, rep, body, headers, remaining_ms,
                may_retry=retriable and attempts < self.retries
                and self._choose(tried | {rep.key}) is not None)
            if relayed:
                return self._outcome(status)
            # not relayed: transport failure (saved=None) or a retryable
            # upstream status whose body we buffered
            attempts += 1
            if not retriable or attempts > self.retries:
                if saved is not None:
                    self._relay_saved(handler, *saved)
                    return self._outcome(saved[0])
                self._reply_json(handler, 502, {
                    "error": "upstream replica failed",
                    "attempts": attempts})
                return "error"
            telemetry.incr("serving.fleet.retry")

    @staticmethod
    def _outcome(status: int) -> str:
        if status < 400:
            return "ok"
        if status == 503:
            return "shed"
        if status == 504:
            return "timeout"
        return "error"

    # the PR-4 HandlingUtils.advanced retryable set, minus 408/429
    # (request-timeout and rate-limit answers follow the request, not the
    # replica — forwarding them to another replica amplifies load)
    RETRYABLE_STATUS = frozenset({500, 502, 503, 504})

    def _attempt(self, handler, rep: Replica, body: bytes,
                 headers: Dict[str, str],
                 remaining_ms: Optional[float], may_retry: bool):
        """One forward to one replica.  Returns (status, relayed, saved):
        relayed=False means nothing was written to the client and the
        caller retries on an alternate replica; `saved` then carries the
        buffered upstream (status, headers, payload) — if it was a
        retryable HTTP response rather than a transport failure — so an
        exhausted retry budget can still relay the real upstream answer."""
        fwd_headers = {k: v for k, v in headers.items()
                       if k.lower() not in _HOP_HEADERS}
        if remaining_ms is not None:
            # the deadline decrement rule: the replica sees only what is
            # left of the client's budget after gateway time (fractional
            # ms — rounding would hand back budget the gateway spent)
            fwd_headers["X-Deadline-Ms"] = f"{remaining_ms:.3f}"
        # the gateway span is the active context on this thread, so the
        # replica's serving.request span becomes its child
        fwd_headers = telemetry.trace_headers(fwd_headers)
        timeout = self.forward_timeout_s
        if remaining_ms is not None:
            timeout = max(0.05, min(timeout, remaining_ms / 1000.0))
        with self._lock:
            rep.inflight += 1
        t0 = time.perf_counter()
        conn = None
        try:
            fault_point("fleet.forward")
            conn = http.client.HTTPConnection(
                rep.info.host, rep.info.port, timeout=timeout)
            conn.request("POST", rep.info.path, body=body,
                         headers=fwd_headers)
            resp = conn.getresponse()
        except Exception:  # noqa: BLE001 — transport failure = dead replica
            self._record_result(rep, ok=False, status=0,
                                dt=time.perf_counter() - t0)
            if conn is not None:
                conn.close()
            return 0, False, None
        try:
            status = resp.status
            if status in self.RETRYABLE_STATUS and may_retry:
                # shed (503), timed out (504), or errored (500/502): the
                # replica is ALIVE (an answer arrived — liveness is the
                # breaker's concern, quality is the canary gate's), but
                # an alternate may do better.  Buffer the answer so an
                # exhausted budget still relays it instead of a generic
                # 502.  Streams never reach here: a chunked body is
                # relayed immediately below, mid-body retries never.
                payload = resp.read()
                self._record_result(rep, ok=True, status=status,
                                    dt=time.perf_counter() - t0)
                return status, False, (status, resp.getheaders(), payload)
            if getattr(resp, "chunked", False):
                self._relay_stream(handler, resp)
                self._record_result(rep, ok=True, status=status,
                                    dt=time.perf_counter() - t0)
                return status, True, None
            payload = resp.read()
            self._record_result(rep, ok=True, status=status,
                                dt=time.perf_counter() - t0)
            self._relay_buffered(handler, resp, payload)
            return status, True, None
        finally:
            conn.close()

    def _record_result(self, rep: Replica, ok: bool, status: int,
                       dt: float):
        """Book one attempt's outcome: breaker, eject counter, per-
        replica histogram, per-version rolling stats."""
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            was_open = rep.breaker.state == "open"
            rep.breaker.record(ok)
            opened = (not was_open) and rep.breaker.state == "open"
            rep.forwarded += 1
            is_error = (not ok) or (status >= 500 and status != 503)
            if is_error:
                rep.errors += 1
            st = self._vstats.setdefault(rep.version, {
                "n": 0, "errors": 0, "lat": deque(maxlen=_LAT_WINDOW)})
            st["n"] += 1
            if is_error:
                st["errors"] += 1
            if ok:
                st["lat"].append(dt)
        if opened:
            telemetry.incr("serving.fleet.eject")
        telemetry.histogram("serving.fleet.replica.latency",
                            replica=rep.key,
                            version=rep.version).observe(dt)
        if opened:
            self._update_gauges()

    # ---- relaying ------------------------------------------------------
    @staticmethod
    def _copy_headers(handler, header_items):
        for k, v in header_items:
            if k.lower() in ("transfer-encoding", "content-length",
                             "connection", "keep-alive", "host",
                             "te", "upgrade", "proxy-connection"):
                continue
            handler.send_header(k, v)

    def _relay_saved(self, handler, status: int, header_items,
                     payload: bytes):
        handler.send_response(status)
        self._copy_headers(handler, header_items)
        handler.send_header("Content-Length", str(len(payload)))
        handler.end_headers()
        handler.wfile.write(payload)

    def _relay_buffered(self, handler, resp, payload: bytes):
        self._relay_saved(handler, resp.status, resp.getheaders(), payload)

    def _relay_stream(self, handler, resp):
        """Chunk-by-chunk pass-through of a streaming reply.  Once the
        first chunk is relayed the request is unretryable (mid-body); a
        failure here drops the client connection."""
        handler.send_response(resp.status)
        self._copy_headers(handler, resp.getheaders())
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        try:
            while True:
                chunk = resp.read(8192)
                if not chunk:
                    break
                handler.wfile.write(
                    f"{len(chunk):X}\r\n".encode() + chunk + b"\r\n")
                handler.wfile.flush()
            handler.wfile.write(b"0\r\n\r\n")
            handler.wfile.flush()
        except OSError:
            handler.close_connection = True

    def _reply_json(self, handler, status: int, payload: dict,
                    extra: Optional[Dict[str, str]] = None):
        body = json.dumps(payload).encode("utf-8")
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        for k, v in (extra or {}).items():
            handler.send_header(k, v)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    # ---- active health probing ----------------------------------------
    def _probe_loop(self):
        while self._running.is_set():
            if self._stop_evt.wait(self.probe_interval_s):
                return
            for rep in self.replicas():
                self._probe_one(rep)

    def _probe_one(self, rep: Replica) -> bool:
        ok = False
        draining = rep.draining
        try:
            fault_point("fleet.health")
            resp = send_request(HTTPRequestData(
                url=f"http://{rep.info.host}:{rep.info.port}/health",
                method="GET"), timeout=2.0)
            ok = resp.status_code == 200
            if ok:
                try:
                    draining = bool(resp.json().get("draining", False))
                except (ValueError, AttributeError):
                    draining = False
        except Exception:  # noqa: BLE001 — incl. injected fleet.health faults
            ok = False
        self._mark_probe(rep, ok, draining)
        return ok

    def _mark_probe(self, rep: Replica, ok: bool, draining: bool):
        with self._lock:
            was_routable = rep.routable()
            rep.draining = draining or rep.drain_requested
            if ok:
                rep.healthy = True
                if rep.breaker.state != "closed":
                    # active reinstatement: a live /health closes the
                    # circuit that passive failures opened
                    rep.breaker.record(True)
                now_routable = rep.routable()
            else:
                rep.healthy = False
                now_routable = False
        if ok and not was_routable and now_routable:
            telemetry.incr("serving.fleet.reinstate")
        elif not ok and was_routable:
            telemetry.incr("serving.fleet.eject")
        self._update_gauges()


class FleetTelemetry:
    """The gateway-side federated telemetry plane (HTTP half of
    `core/telemetry/fleet.py`).

    ``pull_once()`` copies the replica list (one brief gateway-lock
    acquisition), then performs every ``/metrics.json`` GET and the
    merge WITHOUT the routing lock — a slow replica scrape can never
    stall request routing.  The gateway's own registry rides along as
    source ``gateway``, so fleet-level gauges (``serving.fleet.healthy``
    / ``.replicas``) and the merged request histograms land in one view
    the :class:`~mmlspark_tpu.core.telemetry.fleet.SLOEngine` evaluates.

    A pull failure is a health signal, not just a gap in the data: the
    replica is marked unhealthy immediately through the same
    ``_mark_probe`` path as an active probe failure (eject counter,
    gauges, breaker reinstatement later) — this closes the
    registry-TTL-on-read hole where a replica that died between
    registry syncs stayed routable until something else noticed.
    """

    def __init__(self, gateway: "FleetGateway",
                 pull_interval_s: Optional[float] = None,
                 pull_timeout_s: float = 2.0,
                 slos=None,
                 incident_dir: Optional[str] = None,
                 clock=None,
                 worst_traces: int = 3):
        self.gateway = gateway
        self.pull_interval_s = pull_interval_s
        self.pull_timeout_s = float(pull_timeout_s)
        self.worst_traces = int(worst_traces)
        kwargs = {} if clock is None else {"clock": clock}
        self.engine = telemetry.SLOEngine(
            slos if slos is not None else telemetry.default_slos(),
            **kwargs)
        self.recorder = (telemetry.FlightRecorder(incident_dir)
                         if incident_dir else None)
        if self.recorder is not None:
            self.engine.on_transition(self._on_transition)
        self._lock = make_lock("serving.fleet.telemetry")
        self._merged: Optional[dict] = None  #: guarded-by self._lock
        self._last_pull = 0.0  #: guarded-by self._lock (0 = never pulled)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- pulling -------------------------------------------------------

    def _get_json(self, host: str, port: int, path: str) -> Optional[dict]:
        try:
            fault_point("fleet.pull")
            resp = send_request(HTTPRequestData(
                url=f"http://{host}:{port}{path}", method="GET"),
                timeout=self.pull_timeout_s)
            if not resp.ok:
                return None
            return resp.json()
        except Exception:  # noqa: BLE001 — incl. injected fleet.pull faults
            return None

    def pull_once(self) -> dict:
        """One full federated pull + merge + SLO evaluation.  Returns
        the merged fleet view (also cached for the endpoints)."""
        t0 = time.perf_counter()
        reps = self.gateway.replicas()  # brief lock; copied list
        sources: Dict[str, dict] = {
            "gateway": telemetry.export_snapshot(include_spans=False)}
        versions: Dict[str, str] = {}
        failed: List[str] = []
        for rep in reps:
            snap = self._get_json(rep.info.host, rep.info.port,
                                  "/metrics.json")
            if snap is None:
                failed.append(rep.key)
                telemetry.incr("fleet.pull_failed")
                telemetry.incr(f"fleet.pull_failed.{rep.key}")
                # dead-between-syncs replica: unroutable NOW, through
                # the same path as an active probe failure
                self.gateway._mark_probe(rep, ok=False,
                                         draining=rep.draining)
                continue
            sources[rep.key] = snap
            versions[rep.key] = rep.version
        merged = telemetry.merge_snapshots(sources, versions)
        merged["meta"]["gateway"] = self.gateway.name
        merged["meta"]["failed"] = failed
        telemetry.incr("fleet.pull")
        telemetry.gauge("fleet.pull.replicas").set(len(sources) - 1)
        telemetry.histogram("fleet.scrape.latency").observe(
            time.perf_counter() - t0)
        self.engine.observe(merged)
        with self._lock:
            self._merged = merged
            self._last_pull = time.monotonic()
        return merged

    def ensure_fresh(self, max_age_s: Optional[float] = None) -> dict:
        """The cached merged view, re-pulled when never pulled or older
        than `max_age_s` (default: the pull interval, else 0.5 s) — so a
        gateway without a background puller still answers /fleet/*."""
        if max_age_s is None:
            max_age_s = self.pull_interval_s or 0.5
        with self._lock:
            merged = self._merged
            fresh = (merged is not None
                     and time.monotonic() - self._last_pull < max_age_s)
        if fresh:
            return merged
        return self.pull_once()

    def merged(self) -> Optional[dict]:
        with self._lock:
            return self._merged

    # ---- trace stitching -----------------------------------------------

    def fetch_trace(self, trace_id: str) -> dict:
        """Fan one trace id out to every replica's ``/trace/<id>`` and
        stitch the gateway's own spans plus every hop's into one tree."""
        sources: Dict[str, list] = {
            "gateway": telemetry.get_trace(trace_id)}
        for rep in self.gateway.replicas():
            data = self._get_json(rep.info.host, rep.info.port,
                                  f"/trace/{trace_id}")
            if data and data.get("spans"):
                sources[rep.key] = data["spans"]
        return telemetry.stitch_spans(trace_id, sources)

    def _worst_trace_ids(self) -> List[str]:
        """Trace ids of the slowest recent gateway requests — what the
        flight recorder stitches into the incident bundle."""
        reqs = [r for r in telemetry.recent_spans()
                if r.get("name") == "serving.fleet.request"]
        reqs.sort(key=lambda r: r.get("wall_s", 0.0), reverse=True)
        out: List[str] = []
        for r in reqs:
            tid = r.get("trace_id")
            if tid and tid not in out:
                out.append(tid)
            if len(out) >= self.worst_traces:
                break
        return out

    # ---- incident hook -------------------------------------------------

    def _on_transition(self, slo, old: str, new: str, info: dict) -> None:
        if new != "firing" or self.recorder is None:
            return
        try:
            traces = {tid: self.fetch_trace(tid)
                      for tid in self._worst_trace_ids()}
            self.recorder.dump(
                f"slo_{slo.name}",
                merged=self.merged(),
                traces=traces,
                records=telemetry.recent_records()[-100:],
                health=self.gateway.describe(),
                alerts=self.engine.alerts())
        except Exception:  # noqa: BLE001 — recording must never break eval
            pass

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self.pull_interval_s is None or self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fleet-pull-{self.gateway.name}")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.pull_interval_s):
            try:
                self.pull_once()
            except Exception:  # noqa: BLE001 — puller must survive anything
                pass

    def stop(self) -> None:
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def describe(self) -> dict:
        with self._lock:
            age = (time.monotonic() - self._last_pull
                   if self._merged is not None else None)
            n = (self._merged["meta"]["replica_count"]
                 if self._merged else 0)
        return {
            "pull_interval_s": self.pull_interval_s,
            "last_pull_age_s": round(age, 3) if age is not None else None,
            "sources": n,
            "alerts": {a["slo"]: a["state"]
                       for a in self.engine.alerts()},
            "incidents": (self.recorder.bundles()
                          if self.recorder else []),
        }
