"""Model serving: embedded per-host HTTP servers + continuous batching.

Reference: the Spark Serving L6 subsystem (~1.6k LoC; HTTPSourceV2/
HTTPSinkV2/DistributedHTTPSource, SURVEY §2.4) — sub-millisecond data path:
accept, batch, jitted transform, reply over the held socket.

Fleet layer (PR 9, docs/serving.md): FleetGateway routes across replica
pools (p2c balancing, deadline decrement, retry, breaker ejection +
probe reinstatement); RolloutController drives metrics-gated canaries.
"""
from .dsl import DistributedServingServer, StreamingQuery, StreamReader, read_stream
from .fleet import FleetGateway, Replica
from .journal import EpochJournal
from .registry import (
    ServiceRegistry,
    deregister_service,
    list_services,
    register_service,
)
from .rollout import ROLLOUT_METRICS, RolloutController
from .server import (
    CachedRequest,
    ServiceInfo,
    ServingServer,
    WorkerServer,
    make_reply,
    parse_request,
)

__all__ = [
    "EpochJournal",
    "ServingServer",
    "WorkerServer",
    "CachedRequest",
    "ServiceInfo",
    "parse_request",
    "make_reply",
    "ServiceRegistry",
    "register_service",
    "deregister_service",
    "list_services",
    "read_stream",
    "StreamReader",
    "StreamingQuery",
    "DistributedServingServer",
    "FleetGateway",
    "Replica",
    "RolloutController",
    "ROLLOUT_METRICS",
]
