"""Model serving: embedded per-host HTTP servers + continuous batching.

Reference: the Spark Serving L6 subsystem (~1.6k LoC; HTTPSourceV2/
HTTPSinkV2/DistributedHTTPSource, SURVEY §2.4) — sub-millisecond data path:
accept, batch, jitted transform, reply over the held socket.

Fleet layer (PR 9, docs/serving.md): FleetGateway routes across replica
pools (p2c balancing, deadline decrement, retry, breaker ejection +
probe reinstatement); RolloutController drives metrics-gated canaries.

Telemetry plane (PR 15, docs/observability.md): FleetTelemetry federates
every replica's metrics/spans behind ``/fleet/metrics`` and a stitched
``/trace/<id>``, feeds SLO burn-rate alerts, and AutoscaleController
drives replica counts from the merged signals.
"""
from .autoscale import AutoscaleController, CapacityModel
from .dsl import DistributedServingServer, StreamingQuery, StreamReader, read_stream
from .fleet import FleetGateway, FleetTelemetry, Replica
from .journal import EpochJournal
from .registry import (
    ServiceRegistry,
    deregister_service,
    list_services,
    register_service,
)
from .rollout import ROLLOUT_METRICS, RolloutController, drain_and_stop
from .server import (
    CachedRequest,
    ServiceInfo,
    ServingServer,
    WorkerServer,
    make_reply,
    parse_request,
)

__all__ = [
    "EpochJournal",
    "ServingServer",
    "WorkerServer",
    "CachedRequest",
    "ServiceInfo",
    "parse_request",
    "make_reply",
    "ServiceRegistry",
    "register_service",
    "deregister_service",
    "list_services",
    "read_stream",
    "StreamReader",
    "StreamingQuery",
    "DistributedServingServer",
    "FleetGateway",
    "FleetTelemetry",
    "Replica",
    "RolloutController",
    "ROLLOUT_METRICS",
    "drain_and_stop",
    "AutoscaleController",
    "CapacityModel",
]
