"""Model serving: embedded per-host HTTP servers + continuous batching.

Reference: the Spark Serving L6 subsystem (~1.6k LoC; HTTPSourceV2/
HTTPSinkV2/DistributedHTTPSource, SURVEY §2.4) — sub-millisecond data path:
accept, batch, jitted transform, reply over the held socket.
"""
from .dsl import DistributedServingServer, StreamingQuery, StreamReader, read_stream
from .journal import EpochJournal
from .registry import ServiceRegistry, list_services, register_service
from .server import (
    CachedRequest,
    ServiceInfo,
    ServingServer,
    WorkerServer,
    make_reply,
    parse_request,
)

__all__ = [
    "EpochJournal",
    "ServingServer",
    "WorkerServer",
    "CachedRequest",
    "ServiceInfo",
    "parse_request",
    "make_reply",
    "ServiceRegistry",
    "register_service",
    "list_services",
    "read_stream",
    "StreamReader",
    "StreamingQuery",
    "DistributedServingServer",
]
