"""Low-latency model serving: embedded HTTP servers + continuous batching.

Reference: the Spark Serving subsystem (SURVEY §2.4/§3.4) —
HTTPSourceV2.scala:114-735 (per-executor embedded `WorkerServer`, epoch
request queues, `routingTable` correlating request-id -> held exchange,
`historyQueues`/`recoveredPartitions` replay), HTTPSinkV2.scala:55-150
(`replyTo` over the held socket), DistributedHTTPSource.scala (per-JVM shared
server), DriverServiceUtils (:133-194, worker ServiceInfo registry).

TPU-native redesign: one embedded server per host process feeds a
continuous-batching loop — requests are drained into a columnar Table
micro-batch, run through a (jit-compiled) Transformer, and answered over the
held connections.  The data path never leaves the host that accepted the
request (the reference's sub-ms claim rests on the same property).
"""
from __future__ import annotations

import json
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Queue
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core import telemetry
from ..core.schema import Table
from ..io.http.schema import HTTPRequestData, HTTPResponseData
from ..core.flow import deadline_expired, deadline_from_ms
from ..utils.sync import make_lock
from ..utils.fault_tolerance import Overloaded
from ..utils.faults import fault_point
from .journal import EpochJournal

__all__ = ["CachedRequest", "WorkerServer", "ServingServer", "ServiceInfo",
           "StreamWriter", "parse_request", "make_reply"]


@dataclass
class ServiceInfo:
    """What a worker reports to the registry (HTTPSourceV2 ServiceInfo).

    `version` and `weight` feed the fleet control plane (serving/fleet.py):
    the gateway groups replicas by version for canary splits and uses the
    per-replica weight inside a version group."""

    name: str
    host: str
    port: int
    path: str
    version: str = "v1"
    weight: float = 1.0

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.path}"


@dataclass
class CachedRequest:
    """A held exchange: the handler thread parks on `done` until the batch
    loop replies (routingTable entry in the reference)."""

    id: str
    request: HTTPRequestData
    done: threading.Event = field(default_factory=threading.Event)
    response: Optional[HTTPResponseData] = None
    attempts: int = 0
    # streaming reply (stream_to): chunk queue drained by the handler
    # thread; None sentinel closes the stream.  handler_gone flips when
    # the handler thread exits (disconnect, timeout, drain done) so the
    # producer stops writing into a queue nobody reads.
    stream: Optional["Queue[Optional[bytes]]"] = None
    stream_headers: Optional[Dict[str, str]] = None
    handler_gone: threading.Event = field(default_factory=threading.Event)
    # journal-recovered after a restart: no client holds this exchange
    recovered: bool = False
    # absolute time.monotonic() budget from the X-Deadline-Ms header; an
    # expired request is failed fast at batch admission, never computed
    deadline: Optional[float] = None
    # trace context (trace_id, span_id) of the handler's serving.request
    # span: the batch loop runs on ANOTHER thread, so propagation across
    # that hop is explicit — the loop re-activates this via use_trace()
    trace: Optional[Tuple[str, str]] = None
    # when the request entered the queue (monotonic): queue-wait span
    accepted_at: Optional[float] = None


class WorkerServer:
    """Embedded threaded HTTP server with request queue + routing table.

    Reference: HTTPSourceV2.scala WorkerServer (:475-696).
    """

    def __init__(self, name: str, host: str = "127.0.0.1", port: int = 0,
                 path: str = "/", handler_timeout: float = 30.0,
                 journal: Optional["EpochJournal"] = None,
                 max_queue: Optional[int] = 1024):
        self.name = name
        self.path = path if path.startswith("/") else "/" + path
        # the queue object stays unbounded: requeue/recover/journal-replay
        # re-insert ALREADY-ACCEPTED requests and must never block or drop.
        # The bound is enforced at HTTP admission (do_POST sheds with 503 +
        # Retry-After once qsize reaches max_queue) — bounded by default so
        # a stalled consumer can't grow the queue without limit.
        self.queue: "Queue[CachedRequest]" = Queue()  # graftlint: disable=G403
        self.max_queue = None if max_queue is None else int(max_queue)
        # draining: admission sheds everything while held exchanges finish
        # (the graceful half of ServingServer.stop())
        self._draining = threading.Event()
        self.routing: Dict[str, CachedRequest] = {}
        self._routing_lock = make_lock("serving.server.routing")
        self.handler_timeout = handler_timeout
        # epoch-scoped request history for replay-on-retry + commit GC
        # (HTTPSourceV2.scala historyQueues :488-505, commit :555-567)
        self.epoch = 0
        self.history: Dict[int, List[CachedRequest]] = {}
        self._epoch_lock = make_lock("serving.server.epoch")
        # optional disk journal: process-restart persistence (the streaming
        # checkpointLocation analog — see serving/journal.py)
        self.journal = journal
        if journal is not None:
            # recovered requests are already on disk in the journal (it
            # compacts, never truncates) — just requeue them
            for req_id, entity, headers in journal.recovered_requests():
                req = CachedRequest(
                    id=req_id,
                    request=HTTPRequestData(url=self.path, method="POST",
                                            headers=headers, entity=entity),
                    recovered=True)
                with self._routing_lock:
                    self.routing[req.id] = req
                self.queue.put(req)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: a client can pipeline many requests over
            # one connection, so ThreadingHTTPServer's thread-per-CONNECTION
            # cost (and TCP setup) is paid once, not per request; NODELAY
            # stops Nagle from holding back the small JSON replies.
            # Measured on loopback (1-core host): serial p50 0.93ms -> 0.32ms.
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def do_POST(self):
                if self.path.rstrip("/") == "/admin/drain":
                    # remote rolling-drain hook (fleet rollouts): flip to
                    # draining, let the poller watch /health for drained
                    length = int(self.headers.get("Content-Length", 0))
                    if length:
                        self.rfile.read(length)  # keep-alive framing
                    outer.begin_drain()
                    self._reply_bytes(200, b'{"draining": true}',
                                      {"Content-Type": "application/json"})
                    return
                if self.path.rstrip("/") != outer.path.rstrip("/"):
                    self.send_error(404)
                    return
                # continue the caller's trace (X-Trace-Id / X-Span-Id) or
                # root a fresh one; the whole held exchange is one span
                ctx = telemetry.extract_trace(self.headers)
                t0 = time.perf_counter()
                outcome = "error"
                try:
                    with telemetry.span("serving.request", parent_ctx=ctx,
                                        endpoint=outer.path) as sp:
                        outcome = self._handle_post(sp)
                        sp.attrs["outcome"] = outcome
                finally:
                    telemetry.histogram(
                        "serving.request.latency",
                        endpoint=outer.path, outcome=outcome,
                    ).observe(time.perf_counter() - t0)

            def _handle_post(self, sp) -> str:
                """The held-exchange body; returns the outcome label for
                the serving.request.latency histogram ("ok" / "shed" /
                "timeout" / "error")."""
                # keep-alive framing safety: an unread chunked body would be
                # parsed as the NEXT request on this held connection
                if "chunked" in self.headers.get(
                        "Transfer-Encoding", "").lower():
                    self.send_error(501, "chunked transfer not supported")
                    return "error"
                length = int(self.headers.get("Content-Length", 0))
                # read the body BEFORE any early reply: unread bytes would
                # frame as the next request on this keep-alive connection
                body = self.rfile.read(length) if length else b""
                if outer._draining.is_set() or (
                        outer.max_queue is not None
                        and outer.queue.qsize() >= outer.max_queue):
                    # load shedding: a bounded queue answers "not now"
                    # immediately instead of queueing work it can't keep
                    # up with (admission control; 503 is retryable)
                    telemetry.incr("serving.shed")
                    self._reply_bytes(
                        503, b'{"error": "server overloaded, retry later"}',
                        {"Retry-After": "1",
                         "Content-Type": "application/json"})
                    return "shed"
                # the runtime's one deadline model (core/flow.py):
                # malformed budgets mean no deadline
                deadline = deadline_from_ms(
                    self.headers.get("X-Deadline-Ms"))
                req = CachedRequest(
                    id=uuid.uuid4().hex,
                    request=HTTPRequestData(
                        url=self.path, method="POST",
                        headers=dict(self.headers.items()), entity=body,
                    ),
                    deadline=deadline,
                    trace=(sp.trace_id, sp.span_id),
                    accepted_at=time.monotonic(),
                )
                if outer.journal is not None:
                    outer.journal.log_request(req.id, body,
                                              req.request.headers)
                with outer._routing_lock:
                    outer.routing[req.id] = req
                outer.queue.put(req)
                try:
                    if not req.done.wait(outer.handler_timeout):
                        outer._finish(req.id)
                        self.send_error(504, "model timed out")
                        return "timeout"
                    if req.stream is not None:
                        self._drain_stream(req)
                        return "ok"
                finally:
                    # all exits (reply sent, 504, disconnect) tell the
                    # producer this exchange is over — StreamWriter.write
                    # raises instead of filling a queue nobody drains
                    req.handler_gone.set()
                resp = req.response or HTTPResponseData(500, "no response")
                body = resp.entity or b""
                self.send_response(resp.status_code)
                for k, v in resp.headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                sc = resp.status_code
                if sc < 400:
                    return "ok"
                if sc == 503:
                    return "shed"
                if sc == 504:
                    return "timeout"
                return "error"

            def do_GET(self):
                """Observability endpoints on every worker server:
                `/metrics` (Prometheus text exposition of the process
                registry), `/trace/<id>` (one trace's spans + nested
                tree as JSON) and `/trace.json` (the whole span ring as
                Chrome/Perfetto trace-event JSON)."""
                path = self.path.split("?", 1)[0]
                if path.rstrip("/") == "/health":
                    # liveness + drain progress for the fleet gateway's
                    # active prober and rolling-drain poller.  Always 200
                    # while the process serves: "draining" is a routing
                    # hint, not an error.
                    draining = outer._draining.is_set()
                    payload = json.dumps({
                        "status": "draining" if draining else "ok",
                        "draining": draining,
                        "drained": outer.drained(),
                        "queue_depth": outer.queue.qsize(),
                    }).encode("utf-8")
                    self._reply_bytes(200, payload,
                                      {"Content-Type": "application/json"})
                    return
                if path.rstrip("/") == "/metrics":
                    try:
                        # freshen the device gauges on every scrape;
                        # passive no-op when jax/backend is absent
                        telemetry.sample_device_memory()
                    except Exception:
                        pass
                    payload = telemetry.render_prometheus().encode("utf-8")
                    self._reply_bytes(
                        200, payload,
                        {"Content-Type":
                         "text/plain; version=0.0.4; charset=utf-8"})
                    return
                if path.rstrip("/") == "/metrics.json":
                    # the federated-pull wire format: the full registry
                    # as an export_snapshot dict (what the gateway's
                    # FleetTelemetry merges across the pool)
                    try:
                        telemetry.sample_device_memory()
                    except Exception:
                        pass
                    payload = json.dumps(
                        telemetry.export_snapshot(include_spans=False),
                        default=repr).encode("utf-8")
                    self._reply_bytes(200, payload,
                                      {"Content-Type": "application/json"})
                    return
                if path.rstrip("/") == "/trace.json":
                    payload = json.dumps(
                        telemetry.render_chrome_trace()).encode("utf-8")
                    self._reply_bytes(200, payload,
                                      {"Content-Type": "application/json"})
                    return
                if path.startswith("/trace/"):
                    tid = path[len("/trace/"):].strip("/")
                    spans = telemetry.get_trace(tid)
                    if not spans:
                        self._reply_bytes(
                            404, b'{"error": "unknown trace id"}',
                            {"Content-Type": "application/json"})
                        return
                    payload = json.dumps({
                        "trace_id": tid,
                        "spans": spans,
                        "tree": telemetry.span_tree(tid),
                    }).encode("utf-8")
                    self._reply_bytes(200, payload,
                                      {"Content-Type": "application/json"})
                    return
                self.send_error(404)

            def _reply_bytes(self, status: int, body: bytes,
                             headers: Dict[str, str]):
                """Direct small reply (shed/error) preserving keep-alive."""
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _drain_stream(self, req: CachedRequest):
                """Chunked streaming reply (stream_to): each queued buffer
                flushes to the socket as its own chunk, so the client sees
                tokens as they are produced; the 0-length terminator keeps
                the connection reusable."""
                self.send_response(200)
                for k, v in (req.stream_headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        try:
                            chunk = req.stream.get(
                                timeout=outer.handler_timeout)
                        except Empty:
                            # producer stalled without close(): abandon,
                            # and drop the connection so the unterminated
                            # chunked body can't poison keep-alive
                            self.close_connection = True
                            return
                        if chunk is None:
                            break
                        if chunk:
                            self.wfile.write(
                                f"{len(chunk):X}\r\n".encode() + chunk
                                + b"\r\n")
                            self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:  # client went away mid-stream
                    self.close_connection = True

            def log_message(self, *a):  # quiet
                pass

        # a deep listen backlog keeps admission control OURS: a connect
        # burst must reach the shed check (503 + Retry-After) instead of
        # dying in the kernel's SYN queue (ThreadingHTTPServer's default
        # request_queue_size is 5 — connection resets under any burst)
        class _Server(ThreadingHTTPServer):
            request_queue_size = 128

        self._httpd = _Server((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=f"serve-{name}", daemon=True
        )

    @property
    def service_info(self) -> ServiceInfo:
        h, p = self._httpd.server_address[:2]
        return ServiceInfo(self.name, h, p, self.path)

    def start(self):
        self._thread.start()

    def begin_drain(self):
        """Graceful-stop phase 1: new requests shed (503 + Retry-After)
        while already-accepted work keeps flowing to the consumer."""
        self._draining.set()

    def drained(self) -> bool:
        """Nothing queued and no held exchange waiting on a reply."""
        with self._routing_lock:
            held = any(not r.done.is_set() for r in self.routing.values())
        return self.queue.qsize() == 0 and not held

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def _finish(self, request_id: str):
        with self._routing_lock:
            self.routing.pop(request_id, None)

    def _admit(self, req: CachedRequest) -> bool:
        """Deadline gate at batch admission: an expired request is failed
        fast (504, no model compute) — the client's budget is already
        blown, computing the answer would only steal capacity from
        requests that can still make theirs."""
        if deadline_expired(req.deadline):
            telemetry.incr("serving.deadline_expired")
            self.reply_to(req.id, HTTPResponseData(
                504, "deadline exceeded", {"Content-Type": "application/json"},
                b'{"error": "deadline exceeded before processing"}'))
            return False
        return True

    def get_batch(self, max_batch: int, timeout_ms: float,
                  block: bool = True) -> List[CachedRequest]:
        """Drain up to max_batch requests; blocks up to timeout_ms for the
        first one (continuous-batching feed).  `block=False` drains only
        what is already queued (the microbatch-trigger feed).  Requests
        whose X-Deadline-Ms budget already expired are answered 504 here
        and never enter the batch."""
        out: List[CachedRequest] = []
        if block:
            stop_at = time.monotonic() + timeout_ms / 1000.0
            while not out:
                remaining = stop_at - time.monotonic()
                if remaining <= 0:
                    return out
                try:
                    req = self.queue.get(timeout=remaining)
                except Empty:
                    return out
                if self._admit(req):
                    out.append(req)
        while len(out) < max_batch:
            try:
                req = self.queue.get_nowait()
            except Empty:
                break
            if self._admit(req):
                out.append(req)
        return out

    def get_epoch_batch(self, max_batch: int, timeout_ms: float,
                        block: bool = True):
        """(epoch, batch): drain a batch and record it under a fresh epoch
        so an uncommitted consumer death can replay it (the reference's
        per-epoch requestQueues, HTTPSourceV2.scala:646-661)."""
        batch = self.get_batch(max_batch, timeout_ms, block=block)
        with self._epoch_lock:
            self.epoch += 1
            epoch = self.epoch
            if batch:
                self.history[epoch] = list(batch)
        return epoch, batch

    def commit(self, epoch: int):
        """Answered epochs need no replay: GC their history
        (HTTPSinkV2.scala:112 commit -> HTTPSourceV2 :555-567)."""
        with self._epoch_lock:
            for e in [e for e in self.history if e <= epoch]:
                del self.history[e]
        if self.journal is not None:
            self.journal.flush()  # reply lines become durable; may compact

    def recover(self, max_attempts: Optional[int] = None) -> int:
        """Replay every unanswered request of every uncommitted epoch
        (recoveredPartitions, HTTPSourceV2.scala:488-505,608-613).  Returns
        the number of requests requeued.  Answered requests in uncommitted
        epochs are dropped from history, not replayed twice.  With
        `max_attempts`, requests that already burned their retries are
        answered 500 instead of requeued — otherwise a poison batch that
        kills the consumer would crash-loop forever."""
        with self._epoch_lock:
            epochs = sorted(self.history)
            replay: List[CachedRequest] = []
            for e in epochs:
                replay.extend(r for r in self.history[e] if not r.done.is_set())
                del self.history[e]
        requeued = 0
        for req in replay:
            if max_attempts is not None and req.attempts + 1 >= max_attempts:
                self.reply_to(req.id, HTTPResponseData(
                    500, "consumer died", {},
                    b'{"error": "consumer died processing this request"}'))
            else:
                self.requeue(req)
                requeued += 1
        return requeued

    def requeue(self, req: CachedRequest):
        """Replay a failed request (historyQueues/recoveredPartitions)."""
        req.attempts += 1
        self.queue.put(req)

    def stream_to(self, request_id: str,
                  headers: Optional[Dict[str, str]] = None) -> "StreamWriter":
        """Open a chunked streaming reply over the held exchange — the
        token-by-token serving shape for generation (beyond-reference: the
        reference's replyTo is single-shot, HTTPSinkV2.scala:535-553).
        Returns a writer: `.write(bytes)` flushes one chunk to the client
        immediately, `.close()` ends the stream (and journals the reply).
        At-most-once: a crash mid-stream is the client's to retry."""
        with self._routing_lock:
            req = self.routing.pop(request_id, None)
        if req is None:
            raise KeyError(f"no held exchange for request {request_id!r}")
        # chunks of one in-flight reply, drained by the held HTTP
        # exchange as fast as the writer produces them
        req.stream = Queue()  # graftlint: disable=G403
        req.stream_headers = dict(headers or {})
        req.done.set()
        return StreamWriter(self, req)

    def reply_to(self, request_id: str, response: HTTPResponseData):
        """HTTPSinkV2 replyTo: answer over the held exchange."""
        with self._routing_lock:
            req = self.routing.pop(request_id, None)
        if req is not None:
            req.response = response
            req.done.set()
        if self.journal is not None:
            # journal the reply even when the exchange is gone (handler
            # 504 timeout popped it): the model DID process the request,
            # and an un-journaled reply would replay it after restart
            self.journal.log_reply(request_id)


class StreamWriter:
    """Handle returned by WorkerServer.stream_to: chunk sink for one held
    exchange.  Thread-safe hand-off via the request's queue; the handler
    thread owns the socket."""

    def __init__(self, server: WorkerServer, req: CachedRequest):
        self._server = server
        self._id = req.id
        self._req = req
        self._closed = False

    def write(self, data: bytes):
        if self._closed:
            raise ValueError(f"stream for {self._id!r} is closed")
        if self._req.handler_gone.is_set():
            # disconnect or handler timeout: fail the producer loop instead
            # of queueing tokens nobody will read
            raise BrokenPipeError(
                f"client for stream {self._id!r} is gone")
        self._req.stream.put(bytes(data))

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._req.stream.put(None)
        if self._server.journal is not None:
            self._server.journal.log_reply(self._id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def parse_request(batch: List[CachedRequest],
                  schema: Optional[List[str]] = None):
    """JSON request bodies -> columnar micro-batch (IOImplicits.parseRequest).

    Every body must be a JSON object; `schema` restricts/orders the columns.
    Returns (table, id_col): the routing-id column name is chosen to never
    collide with a body field (a client field named 'id' must not clobber
    reply routing).
    """
    from ..core.schema import find_unused_column_name

    rows = []
    for req in batch:
        try:
            rows.append(json.loads(req.request.entity or b"{}"))
        except json.JSONDecodeError:
            rows.append({})
    cols = schema or sorted({k for r in rows for k in r})
    id_col = find_unused_column_name("request_id", cols)
    data: Dict[str, Any] = {id_col: [r.id for r in batch]}
    for c in cols:
        vals = [r.get(c) for r in rows]
        try:
            data[c] = np.asarray(vals)
            if data[c].dtype.kind in "OSU" and not all(
                isinstance(v, str) for v in vals
            ):
                raise ValueError
        except (ValueError, TypeError):
            arr = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                arr[i] = v
            data[c] = arr
    return Table(data), id_col


def make_reply(table: Table, reply_col: str, server: WorkerServer,
               id_col: str = "request_id"):
    """Answer every row's held exchange with the reply column as JSON
    (IOImplicits.makeReply + HTTPSinkV2 write)."""
    ids = table[id_col]
    vals = table[reply_col]
    for i in range(len(table)):
        v = vals[i]
        if isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, np.generic):
            v = v.item()
        body = json.dumps({reply_col: v}).encode("utf-8")
        server.reply_to(
            ids[i],
            HTTPResponseData(200, "OK",
                             {"Content-Type": "application/json"}, body),
        )


class ServingServer:
    """Turn any Transformer into a web service with continuous batching.

    Reference API surface: `spark.readStream.server(...).parseRequest ...
    .makeReply(col).writeStream.server()` (IOImplicits.scala:22-199); here
    the source-query-sink triple is one object.

    model: a Transformer whose transform consumes the parsed request columns
    and produces `reply_col`.

    Engine modes (the reference's trigger duality, SURVEY §2.4 #29):
      - "continuous": a long-running consumer blocks on the queue and drains
        opportunistic batches — the sub-ms path (HTTPSourceV2 continuous).
      - "microbatch": the consumer wakes every `trigger_interval_ms`, drains
        everything that arrived, processes, commits (HTTPSource V1 offsets-
        as-request-counts semantics).

    Every drained batch is an epoch recorded in the server's history;
    commit happens only after all replies are written, so a consumer death
    mid-batch replays the unanswered requests: a supervisor thread restarts
    the loop and calls `recover()` (the Spark task-retry analog).
    """

    def __init__(self, model, reply_col: Optional[str] = None,
                 name: str = "serving",
                 host: str = "127.0.0.1", port: int = 0, path: str = "/",
                 input_schema: Optional[List[str]] = None,
                 max_batch: int = 64, batch_timeout_ms: float = 10.0,
                 max_attempts: int = 2, mode: str = "continuous",
                 trigger_interval_ms: float = 20.0,
                 journal_path: Optional[str] = None,
                 stream_fn: Optional[Any] = None,
                 stream_workers: int = 8,
                 max_queue: Optional[int] = 1024,
                 drain_timeout_s: float = 5.0):
        if mode not in ("continuous", "microbatch"):
            raise ValueError("mode must be 'continuous' or 'microbatch'")
        if stream_fn is None and (model is None or reply_col is None):
            raise ValueError("need model + reply_col, or stream_fn")
        self.model = model
        self.reply_col = reply_col
        # streaming mode: per-request `fn(row) -> iterable of str/bytes`
        # chunks, delivered incrementally over the held exchange
        # (WorkerServer.stream_to).  At-most-once; runs on a pool
        # (`stream_workers` wide) so one slow generation doesn't stall
        # the intake loop.
        self.stream_fn = stream_fn
        self._stream_pool = (
            ThreadPoolExecutor(max_workers=int(stream_workers),
                               thread_name_prefix=f"stream-{name}")
            if stream_fn is not None else None)
        self.input_schema = input_schema
        self.max_batch = int(max_batch)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.max_attempts = int(max_attempts)
        self.mode = mode
        self.trigger_interval_ms = float(trigger_interval_ms)
        # journal_path makes accepted requests durable across process
        # restarts: a fresh ServingServer at the same path replays every
        # journaled-but-unanswered request through the model
        self.journal = (EpochJournal(journal_path)
                        if journal_path is not None else None)
        self.drain_timeout_s = float(drain_timeout_s)
        self.server = WorkerServer(name, host, port, path,
                                   journal=self.journal,
                                   max_queue=max_queue)
        self._running = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self._supervisor: Optional[threading.Thread] = None
        self.stats = {"requests": 0, "batches": 0, "errors": 0,
                      "recoveries": 0, "replayed": 0}

    @property
    def service_info(self) -> ServiceInfo:
        return self.server.service_info

    def _loop(self):
        while self._running.is_set():
            if self.mode == "microbatch":
                time.sleep(self.trigger_interval_ms / 1000.0)
                epoch, batch = self.server.get_epoch_batch(
                    self.max_batch, 0, block=False)
            else:
                epoch, batch = self.server.get_epoch_batch(
                    self.max_batch, self.batch_timeout_ms)
            telemetry.gauge("serving.queue.depth").set(
                self.server.queue.qsize())
            if not batch:
                self.server.commit(epoch)  # empty epochs GC immediately
                continue
            telemetry.histogram("serving.batch.fill").observe(
                len(batch) / max(1, self.max_batch))
            now = time.monotonic()
            for req in batch:
                # attribute each request's queue wait back onto ITS trace:
                # the handler thread's serving.request span is the parent
                if req.trace is not None and req.accepted_at is not None:
                    telemetry.record_span("serving.batcher.queue",
                                          req.trace, now - req.accepted_at)
            # the batch span continues the first traced request's context
            # across the thread hop (a batch serves many traces; the rest
            # keep their queue-wait spans above)
            batch_ctx = next((r.trace for r in batch if r.trace), None)
            with telemetry.use_trace(batch_ctx), \
                    telemetry.span("serving.batcher.batch",
                                   batch_size=len(batch), epoch=epoch):
                # chaos hook: an InjectedCrash here escapes except Exception
                # below and kills the consumer thread mid-batch — exactly the
                # death the supervisor + epoch replay must absorb (the batch
                # is already recorded in the epoch history)
                fault_point("serving.batch_loop")
                if self.stream_fn is not None:
                    # rows come straight from each request's JSON body: the
                    # columnar parse would coerce types batch-dependently (a
                    # lone list becomes an ndarray slice; co-batched ragged
                    # lists stay lists) — stream_fn must see stable types
                    for req in batch:
                        if req.recovered:
                            # a journal-replayed stream has NO client socket:
                            # generating into it would be pure waste.  Streams
                            # are at-most-once; mark replied and move on.
                            self.server.reply_to(req.id, HTTPResponseData(
                                410, "client gone across restart"))
                            continue
                        try:
                            row = json.loads(req.request.entity or b"{}")
                        except json.JSONDecodeError:
                            row = {}
                        if self.input_schema is not None:
                            row = {k: row.get(k) for k in self.input_schema}
                        self._stream_pool.submit(self._stream_one, req.id,
                                                 row, req.trace)
                    self.stats["requests"] += len(batch)
                    self.stats["batches"] += 1
                    self.server.commit(epoch)  # at-most-once past this point
                    continue
                try:
                    table, id_col = parse_request(batch, self.input_schema)
                    out = self.model.transform(table)
                    make_reply(out, self.reply_col, self.server,
                               id_col=id_col)
                    self.stats["requests"] += len(batch)
                    self.stats["batches"] += 1
                    self.server.commit(epoch)
                except Exception as e:  # noqa: BLE001 — serving must survive
                    self.stats["errors"] += 1
                    for req in batch:
                        if req.done.is_set():
                            continue  # make_reply answered it before failing
                        if req.attempts + 1 < self.max_attempts:
                            self.server.requeue(req)
                        else:
                            self.server.reply_to(
                                req.id,
                                HTTPResponseData(
                                    500, "model error", {},
                                    json.dumps({"error": str(e)}).encode(),
                                ),
                            )
                    self.server.commit(epoch)  # history done

    def _stream_one(self, request_id: str, row: Dict[str, Any],
                    trace: Optional[Tuple[str, str]] = None):
        """Produce one request's chunk stream on the pool.

        The chunked exchange opens only once the FIRST chunk exists: a
        stream_fn that fails before producing anything still gets a real
        HTTP 500 (the status line isn't spent yet).  An error after the
        first chunk can only be reported in-band; BrokenPipeError means
        the client left — stop generating."""
        with telemetry.use_trace(trace):
            self._stream_one_traced(request_id, row)

    def _stream_one_traced(self, request_id: str, row: Dict[str, Any]):
        def enc(c):
            return c.encode("utf-8") if isinstance(c, str) else c

        try:
            it = iter(self.stream_fn(row))
            first = next(it, None)
        except Overloaded as e:
            # bounded-intake rejection (e.g. ContinuousBatcher.submit with
            # max_pending): shed, don't error — clients retry 503s
            telemetry.incr("serving.shed")
            self.server.reply_to(request_id, HTTPResponseData(
                503, "overloaded", {"Retry-After": "1",
                                    "Content-Type": "application/json"},
                json.dumps({"error": str(e)}).encode()))
            return
        except Exception as e:  # noqa: BLE001 — pre-stream failure: real 500
            self.stats["errors"] += 1
            self.server.reply_to(request_id, HTTPResponseData(
                500, "stream error", {},
                json.dumps({"error": str(e)}).encode()))
            return
        try:
            writer = self.server.stream_to(
                request_id,
                headers={"Content-Type": "text/plain; charset=utf-8"})
        except KeyError:
            return  # handler timed out and dropped the exchange
        try:
            if first is not None:
                writer.write(enc(first))
            for chunk in it:
                writer.write(enc(chunk))
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 — serving must survive
            self.stats["errors"] += 1
            try:
                writer.write(json.dumps({"error": str(e)}).encode())
            except BrokenPipeError:
                pass
        finally:
            writer.close()

    def _supervise(self):
        """Restart a dead consumer and replay its uncommitted epochs —
        the Spark task-retry + recoveredPartitions path."""
        while self._running.is_set():
            time.sleep(0.05)
            if self._running.is_set() and not self._worker.is_alive():
                self.stats["recoveries"] += 1
                self.stats["replayed"] += self.server.recover(self.max_attempts)
                self._worker = threading.Thread(
                    target=self._loop, daemon=True, name="serving-batch-loop")
                self._worker.start()

    def start(self) -> ServiceInfo:
        self.server.start()
        self._running.set()
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-batch-loop")
        self._worker.start()
        self._supervisor = threading.Thread(target=self._supervise, daemon=True,
                                            name="serving-supervisor")
        self._supervisor.start()
        return self.service_info

    def stop(self, drain: bool = True):
        """Graceful by default: shed new arrivals (503 + Retry-After),
        let the consumer answer everything already accepted (bounded by
        `drain_timeout_s`), then tear the threads down.  `drain=False`
        is the hard stop (process-death simulation; the journal replays
        what was lost)."""
        if drain and self._running.is_set():
            self.server.begin_drain()
            stop_at = time.monotonic() + self.drain_timeout_s
            while time.monotonic() < stop_at and not self.server.drained():
                time.sleep(0.01)
        self._running.clear()
        if self._worker is not None:
            self._worker.join(timeout=5)
        if self._supervisor is not None:
            self._supervisor.join(timeout=5)
        if self._stream_pool is not None:
            # don't wait on in-flight generations: their writers fail fast
            # once the handlers go away, and queued tasks are cancelled so
            # non-daemon pool threads can't block interpreter exit
            self._stream_pool.shutdown(wait=False, cancel_futures=True)
        self.server.stop()
        if self.journal is not None:
            self.journal.close()
