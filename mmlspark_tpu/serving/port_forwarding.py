"""SSH port forwarding for serving behind NAT / VNet.

Reference: io/http/PortForwarding.scala:12 (jsch SSH tunnels keeping serving
endpoints reachable in VNet mode).  Here: a managed `ssh -N -R/-L` subprocess
with keepalive options; command construction is separated from process
launch so it is unit-testable without an SSH server.
"""
from __future__ import annotations

import shutil
import subprocess
from typing import List, Optional

__all__ = ["forwarding_command", "PortForwarder"]


def forwarding_command(
    remote_host: str,
    remote_port: int,
    local_port: int,
    user: Optional[str] = None,
    key_file: Optional[str] = None,
    reverse: bool = True,
    ssh_port: int = 22,
) -> List[str]:
    """Build the ssh tunnel argv.

    reverse=True (-R): expose the local serving port on the remote bastion
    (the VNet mode of the reference); reverse=False (-L): pull a remote
    service to localhost.
    """
    target = f"{user}@{remote_host}" if user else remote_host
    spec = (
        f"{remote_port}:127.0.0.1:{local_port}" if reverse
        else f"{local_port}:127.0.0.1:{remote_port}"
    )
    cmd = [
        "ssh", "-N", "-p", str(ssh_port),
        "-o", "StrictHostKeyChecking=accept-new",
        "-o", "ServerAliveInterval=30",
        "-o", "ExitOnForwardFailure=yes",
        "-R" if reverse else "-L", spec,
    ]
    if key_file:
        cmd += ["-i", key_file]
    cmd.append(target)
    return cmd


class PortForwarder:
    """Managed tunnel subprocess (start/stop/alive)."""

    def __init__(self, *args, **kwargs):
        self.command = forwarding_command(*args, **kwargs)
        self._proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        if self.alive:
            raise RuntimeError("tunnel already running; stop() it first")
        if shutil.which("ssh") is None:
            raise RuntimeError("ssh binary not available for port forwarding")
        self._proc = subprocess.Popen(
            self.command, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
        )

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def stop(self) -> None:
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()  # reap: no zombie
            self._proc = None
