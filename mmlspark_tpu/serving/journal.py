"""Disk-backed request journal: serving survives process restart.

Reference: Spark serving recovers a restarted streaming query from its
`checkpointLocation` — uncommitted epochs are replayed through the pipeline
(HTTPSourceV2.scala:488-505 recoveredPartitions + the streaming engine's
offset log).  The in-memory epoch history in `WorkerServer` covers consumer
(task) death; this journal covers PROCESS death: every accepted request is
appended to an append-only JSONL file before it enters the queue, replies
are journaled as they are written, and a fresh server pointed at the same
journal path requeues every unanswered request (at-least-once processing —
replies to connections that died with the old process are discarded, as the
reference's are).

Durability model: `log_request` flushes to the OS (survives process crash;
an OS crash is out of scope, as it is for the reference's local checkpoint
dirs).  Reply lines are buffered and flushed on epoch commit, so a crash
may replay an already-answered request — at-least-once, never lost.

The file is compacted in place (rewritten with only outstanding requests)
once the dead-record count passes `compact_every`, so long-running servers
don't grow the journal without bound.
"""
from __future__ import annotations

import base64
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["EpochJournal"]


class EpochJournal:
    """Append-only request/reply journal with in-place compaction."""

    def __init__(self, path: str, compact_every: int = 1024):
        self.path = path
        self.compact_every = int(compact_every)
        self._lock = threading.Lock()
        # id -> (entity, headers) of journaled-but-unanswered requests;
        # doubles as the compaction source and the recovery result
        self._outstanding: Dict[str, Tuple[bytes, dict]] = {}
        self._dead_records = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._recovered = self._load()
        # append-only log: records are flushed per write and replay
        # tolerates a torn tail; atomicity (tmp+fsync+replace) lives in
        # _compact_locked, which rewrites the whole file
        self._f = open(path, "a", encoding="utf-8")  # graftlint: disable=G404
        if self._recovered:
            # drop answered records from the recovered file ATOMICALLY
            # (tmp + rename) — the unanswered requests are never off disk,
            # so a crash at any point during startup cannot lose them
            with self._lock:
                self._compact_locked()

    # ---- write path ----------------------------------------------------
    def log_request(self, req_id: str, entity: bytes,
                    headers: Optional[dict] = None):
        """Journal an accepted request; flushed so a process crash after
        accept cannot lose it."""
        rec = {"t": "req", "id": req_id,
               "e": base64.b64encode(entity or b"").decode("ascii")}
        if headers:
            rec["h"] = dict(headers)
        with self._lock:
            self._outstanding[req_id] = (entity or b"", dict(headers or {}))
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()

    def log_reply(self, req_id: str):
        """Journal an answered request (buffered; flushed on commit)."""
        with self._lock:
            if req_id not in self._outstanding:
                return
            del self._outstanding[req_id]
            self._dead_records += 2  # the req line + this reply line
            self._f.write(json.dumps({"t": "rep", "id": req_id}) + "\n")

    def flush(self):
        """Epoch-commit barrier: replies written so far become durable; may
        trigger compaction."""
        with self._lock:
            self._f.flush()
            if self._dead_records >= self.compact_every:
                self._compact_locked()

    def close(self):
        with self._lock:
            self._f.close()

    # ---- recovery ------------------------------------------------------
    def recovered_requests(self) -> List[Tuple[str, bytes, dict]]:
        """(id, entity, headers) of every request journaled by a previous
        process and never answered — requeue these on start."""
        out, self._recovered = self._recovered, []
        return out

    def _load(self) -> List[Tuple[str, bytes, dict]]:
        """Read a previous process's journal: unanswered requests become
        both the recovery result and this journal's initial outstanding
        set (they stay journaled under their original ids until answered —
        the file is never truncated, only compacted atomically)."""
        if not os.path.exists(self.path):
            return []
        reqs: Dict[str, Tuple[bytes, dict]] = {}
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail write from the crash: ignore
                if not isinstance(rec, dict) or "id" not in rec:
                    # valid JSON but not a journal record (torn write that
                    # happens to parse, or foreign junk): recovery must
                    # salvage the rest of the file, not die on one line
                    continue
                if rec.get("t") == "req":
                    try:
                        entity = base64.b64decode(rec.get("e", ""))
                    except (ValueError, TypeError):
                        continue  # corrupt payload: unrecoverable record
                    reqs[rec["id"]] = (entity, rec.get("h", {}))
                elif rec.get("t") == "rep":
                    reqs.pop(rec["id"], None)
        self._outstanding = dict(reqs)
        return [(i, e, h) for i, (e, h) in reqs.items()]

    def _compact_locked(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for req_id, (entity, headers) in self._outstanding.items():
                rec = {"t": "req", "id": req_id,
                       "e": base64.b64encode(entity).decode("ascii")}
                if headers:
                    rec["h"] = headers
                f.write(json.dumps(rec) + "\n")
            f.flush()
            # fsync BEFORE the rename: os.replace is atomic for the name,
            # but without it a power loss can leave the new name pointing
            # at un-persisted blocks — losing every outstanding request
            os.fsync(f.fileno())
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a", encoding="utf-8")
        self._dead_records = 0
