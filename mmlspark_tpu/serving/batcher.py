"""Continuous batching for autoregressive decode.

Beyond-reference serving capability (the reference batches fixed-function
transforms; it has no decode loop at all): many concurrent generation
streams share ONE jitted slot-decode step per token tick.  Each request
owns a slot in a static [S, max_len, ...] KV cache; slots sit at their
OWN positions (`decode_step` slot mode, models/transformer.py), so
requests admit/finish independently — a new stream joins the running
batch the tick after an old one leaves, no recompile (the vLLM-style
continuous-batching shape, minus paging).

Host loop per tick: admit pending prompts into free slots (one prefill
forward each; its padded cache rows overwrite the slot), one batched
decode step for ALL slots, emit each live slot's token to its stream.
Greedy decode — the serving-stream shape; outputs are exactly
`generate()`'s for every stream regardless of co-tenancy (tested).

Compose with serving: `stream_reply(lambda row: batcher.stream_text(...))`
gives token-by-token HTTP with cross-request batching on the device.
"""
from __future__ import annotations

import threading
from queue import Empty, Queue
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ContinuousBatcher", "TokenStream"]


class TokenStream:
    """Iterator over one request's generated token ids (host ints).
    Blocks until tokens arrive; ends when the request finishes."""

    def __init__(self):
        self._q: "Queue[Optional[int]]" = Queue()

    def __iter__(self) -> Iterator[int]:
        while True:
            tok = self._q.get()
            if tok is None:
                return
            yield tok

    def tokens(self) -> List[int]:
        """Drain the whole stream (blocking)."""
        return list(self)


class _Request:
    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 eos_id: Optional[int]):
        self.prompt = prompt
        self.max_new = int(max_new_tokens)
        self.eos_id = eos_id
        self.stream = TokenStream()
        self.emitted = 0


class ContinuousBatcher:
    """Schedule many decode streams onto one slotted device batch.

    model: a TransformerLM; variables: its weights.  `max_slots` is the
    device batch width (a compile-time constant — one compiled step
    serves every mix of tenants).
    """

    def __init__(self, model, variables, max_slots: int = 8,
                 idle_sleep_s: float = 0.001,
                 kv_cache_dtype: str = None):
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(f"kv_cache_dtype must be None or 'int8', "
                             f"got {kv_cache_dtype!r}")
        self.model = model
        self.variables = {c: v for c, v in variables.items()
                          if c != "kvcache"}
        self.max_slots = int(max_slots)
        self.idle_sleep_s = float(idle_sleep_s)
        self.kv_cache_dtype = kv_cache_dtype
        s, L = self.max_slots, model.max_len
        h = model.kv_heads
        d = model.embed_dim // model.num_heads
        dt = jnp.float32 if model.dtype == jnp.float32 else model.dtype
        if kv_cache_dtype == "int8":
            # 4x the co-tenant density per HBM byte: int8 rows + f32
            # per-(pos, head) scales (ops/quant.quantize_kv_row)
            self._cache = tuple(
                (jnp.zeros((s, L, h, d), jnp.int8),
                 jnp.zeros((s, L, h), jnp.float32),
                 jnp.zeros((s, L, h, d), jnp.int8),
                 jnp.zeros((s, L, h), jnp.float32))
                for _ in range(model.num_layers))
        else:
            self._cache = tuple(
                (jnp.zeros((s, L, h, d), dt), jnp.zeros((s, L, h, d), dt))
                for _ in range(model.num_layers))
        self._pos = np.zeros(s, np.int32)
        self._tok = np.zeros(s, np.int32)
        self._live: List[Optional[_Request]] = [None] * s
        self._pending: "Queue[_Request]" = Queue()
        self._running = threading.Event()
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        self._step = jax.jit(
            lambda v, t, c, p: self.model.apply(
                v, t, c, p, method=self.model.decode_step))
        # whole-slot overwrite: a newly admitted request's padded cache
        # rows replace slot `i` across every layer in one jitted update
        self._load = jax.jit(
            lambda c, rows, i: jax.tree.map(
                lambda dst, src: dst.at[i].set(src[0].astype(dst.dtype)),
                c, rows))

    # ---- client side ---------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               eos_id: Optional[int] = None) -> TokenStream:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        if len(prompt) + max_new_tokens > self.model.max_len:
            raise ValueError(
                f"prompt {len(prompt)} + {max_new_tokens} exceeds "
                f"max_len {self.model.max_len}")
        if self._stopped:
            # a late submit racing stop() would otherwise wait forever on
            # a stream nobody will ever close
            raise RuntimeError("ContinuousBatcher is stopped")
        req = _Request(prompt, max_new_tokens, eos_id)
        self._pending.put(req)
        return req.stream

    def stream_text(self, tokenizer, text: str,
                    max_new_tokens: int = 32) -> Iterator[str]:
        """serving.stream_reply-ready: text in, decoded token chunks out."""
        ids = tokenizer.encode(text, append_eos=False)
        for tok in self.submit(ids, max_new_tokens,
                               eos_id=tokenizer.eos_id):
            piece = tokenizer.decode([tok])
            if piece:
                yield piece + " "

    # ---- scheduler loop ------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        self._running.set()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="continuous-batcher")
        self._thread.start()
        return self

    def stop(self):
        self._stopped = True
        self._running.clear()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # unblock any consumers still waiting on admitted streams
        for req in self._live:
            if req is not None:
                req.stream._q.put(None)
        while True:
            try:
                self._pending.get_nowait().stream._q.put(None)
            except Empty:
                break

    def _admit(self, slot: int, req: _Request):
        from ..models.generation import _prefill_cache

        logits, cache = _prefill_cache(self.model, self.variables,
                                       jnp.asarray(req.prompt[None]),
                                       self.kv_cache_dtype)
        self._cache = self._load(self._cache, cache, slot)
        first = int(jnp.argmax(logits[0, -1]))
        self._live[slot] = req
        self._pos[slot] = len(req.prompt)
        self._tok[slot] = first
        self._emit(slot, first)

    def _emit(self, slot: int, tok: int):
        req = self._live[slot]
        req.emitted += 1
        req.stream._q.put(tok)
        done = (req.emitted >= req.max_new
                or (req.eos_id is not None and tok == req.eos_id)
                or int(self._pos[slot]) + 1 >= self.model.max_len)
        if done:
            req.stream._q.put(None)
            self._live[slot] = None

    def _loop(self):
        while self._running.is_set():
            # admit as many pending requests as there are free slots
            for slot in range(self.max_slots):
                if self._live[slot] is None:
                    try:
                        req = self._pending.get_nowait()
                    except Empty:
                        break
                    self._admit(slot, req)
            active = [s for s in range(self.max_slots)
                      if self._live[s] is not None]
            if not active:
                try:
                    req = self._pending.get(timeout=self.idle_sleep_s)
                except Empty:
                    continue
                self._admit(0, req)
                active = [0] if self._live[0] is not None else []
                if not active:
                    continue
            # ONE batched step for every slot (free slots compute too —
            # their pos 0 writes are dead, an admit overwrites the rows)
            lg, self._cache = self._step(
                self.variables, jnp.asarray(self._tok)[:, None],
                self._cache, jnp.asarray(self._pos))
            nxt = np.asarray(jnp.argmax(lg[:, 0], axis=-1), np.int32)
            for slot in active:
                self._pos[slot] += 1
                self._tok[slot] = nxt[slot]
                self._emit(slot, int(nxt[slot]))
