"""Continuous batching for autoregressive decode.

Beyond-reference serving capability (the reference batches fixed-function
transforms; it has no decode loop at all): many concurrent generation
streams share ONE jitted slot-decode step per token tick.  Each request
owns a slot in a static [S, max_len, ...] KV cache; slots sit at their
OWN positions (`decode_step` slot mode, models/transformer.py), so
requests admit/finish independently — a new stream joins the running
batch the tick after an old one leaves, no recompile (the vLLM-style
continuous-batching shape).  `paged=True` swaps the per-slot cache for
shared page pools + a page table (vLLM paged KV): HBM is pay-per-page,
so co-tenant density stops being bounded by max_slots * max_len.

Host loop per tick: admit pending prompts into free slots (one prefill
forward each; its padded cache rows overwrite the slot), one batched
decode step for ALL slots, emit each live slot's token to its stream.
Greedy decode — the serving-stream shape; outputs are exactly
`generate()`'s for every stream regardless of co-tenancy (tested).

Compose with serving: `stream_reply(lambda row: batcher.stream_text(...))`
gives token-by-token HTTP with cross-request batching on the device.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from queue import Empty, Queue
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import telemetry
from ..core.flow import AdmissionStage, FlowGraph, Stage
from ..utils.sync import make_rlock

__all__ = ["ContinuousBatcher", "PrefillStage", "TokenStream"]


class PrefillStage(Stage):
    """Host-side prompt packing for admission prefill buckets, as a
    registered flow stage: bucket i+1 packs on a flow worker while
    bucket i's prefill forward occupies the device.  The bounded credit
    budget caps how many packed buckets stage ahead of the device (lint
    rule G405 holds every registered Stage subclass to one)."""

    name = "prefill"
    credits = 4


class TokenStream:
    """Iterator over one request's generated token ids (host ints).
    Blocks until tokens arrive; ends when the request finishes.  A
    request the scheduler had to abandon (e.g. its paged reservation
    can never fit after a later prefix registration shrank the pool)
    closes the stream with `error` set and iteration raises it —
    consumers must never block forever on a request that cannot run."""

    def __init__(self):
        # one request's tokens, capped by its max_new_tokens; bounding it
        # would let one slow client stall the batch loop for every slot
        self._q: "Queue[Optional[int]]" = Queue()  # graftlint: disable=G403
        self.error: Optional[Exception] = None

    def __iter__(self) -> Iterator[int]:
        while True:
            tok = self._q.get()
            if tok is None:
                if self.error is not None:
                    raise self.error
                return
            yield tok

    def tokens(self) -> List[int]:
        """Drain the whole stream (blocking)."""
        return list(self)


class _Request:
    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 eos_id: Optional[int], prefix: Optional[int] = None,
                 deadline: Optional[float] = None):
        self.prompt = prompt          # FULL ids (shared prefix + suffix)
        self.max_new = int(max_new_tokens)
        self.eos_id = eos_id
        self.prefix = prefix          # register_prefix handle, or None
        self.deadline = deadline      # absolute monotonic admission budget
        self.stream = TokenStream()
        self.emitted = 0
        # submitter's trace context: admission latency is attributed back
        # to the submitting request's span (the loop is another thread)
        self.trace = telemetry.current_context()
        self.submitted_at = time.monotonic()


class ContinuousBatcher:
    """Schedule many decode streams onto one slotted device batch.

    model: a TransformerLM; variables: its weights.  `max_slots` is the
    device batch width (a compile-time constant — one compiled step
    serves every mix of tenants).

    `draft_model`/`draft_variables` turn on SPECULATIVE continuous
    batching (vLLM-style): each tick the draft proposes `gamma` tokens
    for every slot ((gamma+1) cheap slot steps on a dense draft cache),
    then ONE target slot-BLOCK step verifies all slots' proposals at
    their own positions — up to gamma+1 tokens emitted per slot per
    target forward, outputs still EXACTLY generate()'s greedy tokens per
    stream (the per-slot speculative-decoding argument, composed with
    co-tenancy; tested).  The draft must share the target's vocabulary.
    """

    def __init__(self, model, variables, max_slots: int = 8,
                 idle_sleep_s: float = 0.001,
                 max_pending: Optional[int] = None,
                 kv_cache_dtype: str = None,
                 paged: bool = False, page_size: int = 64,
                 num_pages: Optional[int] = None,
                 draft_model=None, draft_variables=None, gamma: int = 4,
                 feed=None):
        if kv_cache_dtype not in (None, "int8"):
            raise ValueError(f"kv_cache_dtype must be None or 'int8', "
                             f"got {kv_cache_dtype!r}")
        if (draft_model is None) != (draft_variables is None):
            raise ValueError("draft_model and draft_variables go together")
        if draft_model is not None:
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if gamma < 1:
                raise ValueError(f"gamma must be >= 1, got {gamma}")
            if model.moe_experts > 0 and model.moe_capacity < model.moe_experts:
                # MoE expert capacity scales with the tokens per forward,
                # so a [B, gamma+1] verify block could drop tokens that
                # s=1 decode keeps — breaking the exactness contract.
                # capacity_factor >= num_experts makes every block width
                # drop-free (see TransformerLM.moe_capacity).
                raise ValueError(
                    "speculative batching with MoE needs drop-free "
                    f"capacity: set moe_capacity >= moe_experts "
                    f"({model.moe_experts}), got {model.moe_capacity}")
        from ..io.feed import DeviceFeed

        self.model = model
        self.variables = {c: v for c, v in variables.items()
                          if c != "kvcache"}
        # every host->device upload (per-tick token/pos/page-table vectors,
        # admission prefill batches) rides the shared feed engine: the
        # tick's 2-3 small arrays byte-pack into ONE device_put — through a
        # high-latency link each separate transfer is a full round trip on
        # the decode tick's critical path.  Callers may inject a
        # configured feed (`feed=`) — e.g. one carrying the autotuner's
        # winner (io.feed.load_tuned) or a meshed sharded engine — and
        # the prefill uploads inherit it; the default feed still adopts
        # MMLSPARK_FEED_TUNED on its own
        self._feed = feed if feed is not None else DeviceFeed()
        self.max_slots = int(max_slots)
        self.idle_sleep_s = float(idle_sleep_s)
        # bounded intake: submit() sheds (raises Overloaded) once this many
        # requests wait for a slot; None = unbounded (the seed behavior)
        self.max_pending = None if max_pending is None else int(max_pending)
        self.kv_cache_dtype = kv_cache_dtype
        self.paged = bool(paged)
        self.draft_model = draft_model
        self.gamma = int(gamma) if draft_model is not None else 0
        s, L = self.max_slots, model.max_len
        h = model.kv_heads
        d = model.embed_dim // model.num_heads
        dt = jnp.float32 if model.dtype == jnp.float32 else model.dtype
        if self.paged:
            # vLLM-style paged KV: per-layer PAGE POOLS shared by every
            # slot + a [S, MP] page table.  HBM cost is pay-per-page
            # (Σ ceil(live_len_i / page) pages) instead of S * max_len —
            # the stream-density lever past int8's 4x, and it composes
            # with kv_cache_dtype="int8".  Admission reserves each
            # request's WORST-CASE page count up front (counts only;
            # allocation stays lazy), so a running stream can never hit
            # pool exhaustion mid-decode.  Physical page 0 is the
            # write-trash page: free slots' dead writes and unallocated
            # table entries land there harmlessly (gathered trash rows
            # sit at positions the <= pos validity mask already hides).
            if L % int(page_size):
                raise ValueError(
                    f"page_size {page_size} must divide max_len {L}")
            self.page_size = int(page_size)
            self._mp = L // self.page_size          # max pages per slot
            self._np = (int(num_pages) if num_pages is not None
                        else s * self._mp + 1)      # default: dense parity
            if self._np < 2:
                raise ValueError("num_pages must be >= 2 (page 0 is trash)")
            shape4 = (self._np, self.page_size, h, d)
            shape3 = (self._np, self.page_size, h)
            self._free: List[int] = list(range(1, self._np))
            self._avail = len(self._free)           # unreserved budget
            self._slot_pages: List[List[int]] = [[] for _ in range(s)]
            self._slot_reserved = [0] * s
            self._slot_shared = [0] * s   # leading SHARED-prefix pages
            self._table = np.zeros((s, self._mp), np.int32)
            self._prefixes: dict = {}     # handle -> shared-prefix record
            self._next_prefix = 1
        else:
            shape4, shape3 = (s, L, h, d), (s, L, h)
        if kv_cache_dtype == "int8":
            # 4x the co-tenant density per HBM byte: int8 rows + f32
            # per-(pos, head) scales (ops/quant.quantize_kv_row)
            self._cache = tuple(
                (jnp.zeros(shape4, jnp.int8),
                 jnp.zeros(shape3, jnp.float32),
                 jnp.zeros(shape4, jnp.int8),
                 jnp.zeros(shape3, jnp.float32))
                for _ in range(model.num_layers))
        else:
            self._cache = tuple(
                (jnp.zeros(shape4, dt), jnp.zeros(shape4, dt))
                for _ in range(model.num_layers))
        self._pos = np.zeros(s, np.int32)
        self._tok = np.zeros(s, np.int32)
        self._live: List[Optional[_Request]] = [None] * s
        # the intake is a graftflow AdmissionStage: bounded shed at
        # submit() (Overloaded/503 past max_pending), expired-deadline
        # reaping, and graceful drain are the runtime's one code path —
        # with the batcher's historical counter/gauge names mirrored
        self._intake = AdmissionStage(
            max_pending=self.max_pending, label="batcher",
            shed_counter="batcher.shed",
            expired_counter="batcher.deadline_expired",
            depth_gauge="serving.batcher.queue_depth")
        # loop-thread-only FIFO between intake and admission: paged mode
        # may defer the queue head until enough pages free up (alias —
        # reap/drain mutate the deque in place, so it stays valid)
        self._buffer: "deque[_Request]" = self._intake.buffer
        # control ops (prefix register/release) serviced by the loop
        # thread, which owns the pool/free-list/device cache; low-rate
        # and must never drop or block the caller
        self._ctl: Queue = Queue()  # graftlint: disable=G403
        self._running = threading.Event()
        self._stopped = False
        # serializes the stopped-check+enqueue in submit() against stop()'s
        # drain: without it a submit racing stop can enqueue after the
        # drain, leaving a stream whose consumer blocks forever.  RLock:
        # _ctl_call executes control ops INLINE under this lock when no
        # loop thread runs, and _exec_release_prefix re-acquires it
        self._submit_lock = make_rlock("serving.batcher.submit")
        self._thread: Optional[threading.Thread] = None
        self._step = jax.jit(
            lambda v, t, c, p, pt: self.model.apply(
                v, t, c, p, pt, method=self.model.decode_step))
        # whole-slot overwrite: admitted requests' padded cache rows
        # replace their slots across every layer in one jitted update;
        # pad rows carry the OUT-OF-RANGE slot id S so mode="drop"
        # discards them (NOT -1: jax wraps negative indices numpy-style
        # BEFORE the bounds check, which would corrupt the last slot)
        self._load_many = jax.jit(
            lambda c, rows, slots: jax.tree.map(
                lambda dst, src: dst.at[slots].set(
                    src.astype(dst.dtype), mode="drop"),
                c, rows))
        # paged admit: each row's prefill reshapes into [MP, page, ...]
        # blocks and scatters into the pools at its page ids (flat
        # [K*MP]); blocks past an allocation carry the out-of-range id
        # NP and drop
        self._load_paged_many = jax.jit(
            lambda c, rows, ids: jax.tree.map(
                lambda pool, r: pool.at[ids].set(
                    r.reshape(ids.shape[0], pool.shape[1],
                              *r.shape[2:]).astype(pool.dtype),
                    mode="drop"),
                c, rows))
        if draft_model is not None:
            # speculative mode: the draft keeps a plain DENSE f32/bf16
            # slot cache (it is the small/cheap model; paging and int8
            # buy nothing there) at the same logical positions as the
            # target's cache
            self.draft_variables = {c: v for c, v in draft_variables.items()
                                    if c != "kvcache"}
            dL = draft_model.max_len
            dh = draft_model.kv_heads
            dd = draft_model.embed_dim // draft_model.num_heads
            ddt = (jnp.float32 if draft_model.dtype == jnp.float32
                   else draft_model.dtype)
            self._d_cache = tuple(
                (jnp.zeros((s, dL, dh, dd), ddt),
                 jnp.zeros((s, dL, dh, dd), ddt))
                for _ in range(draft_model.num_layers))
            self._d_step = jax.jit(
                lambda v, t, c, p: self.draft_model.apply(
                    v, t, c, p, None, method=self.draft_model.decode_step))

    def _page_ceiling(self) -> int:
        """Pages that can EVER be simultaneously free for one request:
        the pool minus every registered prefix's held pages.  submit()'s
        reject and _try_admit()'s drop are the two ends of the same
        admission invariant and MUST share this expression — divergence
        would let submit accept a request the scheduler then errors (or
        silently wedge valid ones)."""
        return self._np - 1 - sum(
            r["shared"] for r in self._prefixes.values())

    def _worst_pages(self, prompt_len: int, max_new: int,
                     shared_pages: int = 0) -> int:
        """Worst-case page count for one request — THE reservation
        invariant: submit()'s rejection and _try_admit()'s reservation
        must both use exactly this, or just-in-time growth in the loop
        can pop an empty free list mid-decode.  Speculative mode writes
        up to `gamma` rows past the emitted position per verify block,
        so the reservation covers them too.  A shared prefix's leading
        pages are the HANDLE's, not the request's."""
        return min(-(-(prompt_len + max_new + self.gamma)
                     // self.page_size), self._mp) - shared_pages

    # ---- shared-prefix caching (paged mode) ----------------------------
    # The page pool, free list, and device cache are LOOP-THREAD-OWNED;
    # prefix registration/release therefore route through a control queue
    # the loop drains each tick (executed inline when the loop isn't
    # running — the common register-at-setup case).

    def _ctl_call(self, op, payload):
        rec = {"op": op, "payload": payload, "event": threading.Event(),
               "result": None, "error": None}
        with self._submit_lock:
            if self._stopped:
                raise RuntimeError("ContinuousBatcher is stopped")
            # inline only while no loop thread can possibly be running —
            # a thread that is merely STOPPING may still be mid-tick,
            # and the queue is drained (with errors) by stop() after the
            # join, so enqueueing is always safe when it is alive.  The
            # inline execution stays UNDER the lock: start() also takes
            # it, so a racing start() cannot spawn a ticking loop while
            # the caller thread mutates the loop-owned pool state.
            alive = self._thread is not None and self._thread.is_alive()
            if alive:
                self._ctl.put(rec)
            else:
                return op(payload)
        if not rec["event"].wait(timeout=300):
            raise RuntimeError("batcher loop did not service the request")
        if rec["error"] is not None:
            raise rec["error"]
        return rec["result"]

    def register_prefix(self, prefix_ids) -> int:
        """Prefill a shared prompt prefix (system prompt) ONCE into
        dedicated read-only pages; `submit(..., prefix=handle)` requests
        then reuse them — admission prefills only each request's suffix,
        attending over the shared pages through its page table.  Only
        the full pages share (floor(len/page) * page tokens); the
        remainder rides with each request's suffix.  Write isolation is
        structural: request writes start at the first non-shared
        position, whose table entry is always a request-owned page.
        Returns a handle for submit()/release_prefix()."""
        if not self.paged:
            raise ValueError("prefix caching needs paged=True")
        ids = np.asarray(prefix_ids, np.int32).reshape(-1)
        if len(ids) < 1:
            raise ValueError("empty prefix")
        if len(ids) + 1 + self.gamma > self.model.max_len:
            raise ValueError("prefix leaves no room to generate")
        if (self.draft_model is not None
                and len(ids) + 1 + self.gamma > self.draft_model.max_len):
            # mirror submit()'s limit: the dense draft cache must hold the
            # FULL prompt (prefix + suffix), and _bucket caps prefill
            # widths at the draft's max_len — without this check a long
            # prefix dies later in an opaque broadcast error
            raise ValueError(
                f"prefix of {len(ids)} tokens exceeds the draft model's "
                f"max_len {self.draft_model.max_len} - 1 - gamma "
                f"{self.gamma} (speculative mode prefills the full "
                "prompt into the draft cache)")
        return self._ctl_call(self._exec_register_prefix, ids)

    def release_prefix(self, handle: int):
        """Free a prefix's shared pages.  Refuses while any live or
        pending request still uses it."""
        return self._ctl_call(self._exec_release_prefix, int(handle))

    def _exec_register_prefix(self, ids) -> int:
        from ..models.generation import _prefill_cache

        shared = len(ids) // self.page_size          # full pages only
        if shared > self._avail:
            raise ValueError(
                f"prefix needs {shared} pages but only {self._avail} "
                "are unreserved")
        self._avail -= shared
        pages = [self._free.pop() for _ in range(shared)]
        try:
            b = self._bucket(len(ids))
            padded = np.zeros((1, b), np.int32)
            padded[0, :len(ids)] = ids
            logits, cache = _prefill_cache(self.model, self.variables,
                                           jnp.asarray(padded),
                                           self.kv_cache_dtype)
            if shared:
                page_ids = np.full(self._mp, self._np, np.int32)
                page_ids[:shared] = pages
                self._cache = self._load_paged_many(self._cache, cache,
                                                    jnp.asarray(page_ids))
        except Exception:
            # a failed prefill must not leak the pool allocation
            self._free.extend(pages)
            self._avail += shared
            raise
        handle = self._next_prefix
        self._next_prefix += 1
        # under _submit_lock (re-entrant on the inline path): submit()
        # iterates _prefixes.values() for the page ceiling under this
        # lock from client threads — an unguarded insert from the loop
        # thread would intermittently blow up that iteration
        with self._submit_lock:
            self._prefixes[handle] = {
                "ids": ids, "pages": pages, "shared": shared,
                # logits at the last prefix position: the first generated
                # token when a request adds no suffix
                "last_logits": np.asarray(logits[0, len(ids) - 1]),
                "refs": 0,
            }
        return handle

    def _exec_release_prefix(self, handle: int):
        # the refs check + delete serialize against submit()'s refs
        # increment (both under _submit_lock), so release can never slip
        # between a submit's validation and its increment
        with self._submit_lock:
            rec = self._prefixes[handle]
            if rec["refs"] > 0:
                raise ValueError(f"prefix {handle} still has "
                                 f"{rec['refs']} active request(s)")
            del self._prefixes[handle]
        self._free.extend(rec["pages"])
        self._avail += rec["shared"]

    # ---- client side ---------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               eos_id: Optional[int] = None,
               prefix: Optional[int] = None,
               deadline: Optional[float] = None) -> TokenStream:
        """`prefix`: a register_prefix handle — `prompt_ids` is then the
        SUFFIX appended to the shared prefix (may be empty), and
        admission prefills only the suffix.

        `deadline`: absolute `time.monotonic()` budget for ADMISSION — a
        request still waiting for a slot past it is failed fast with a
        TimeoutError on its stream instead of being computed (already
        admitted streams run to completion).  Load shedding: when
        `max_pending` is set and that many requests already wait,
        submit raises Overloaded (serving maps it to 503 + Retry-After)."""
        self._intake.shed_check()
        shared_pages = 0
        if prefix is not None:
            if not self.paged:
                raise ValueError("prefix caching needs paged=True")
            try:
                rec = self._prefixes[prefix]
            except KeyError:
                raise ValueError(f"unknown or released prefix {prefix}")
            prompt = np.concatenate(
                [rec["ids"], np.asarray(prompt_ids, np.int32).reshape(-1)])
            shared_pages = rec["shared"]
        else:
            prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        limit = self.model.max_len - self.gamma
        if self.draft_model is not None:
            # draft writes ride to the same positions (+gamma lookahead)
            limit = min(limit, self.draft_model.max_len - self.gamma)
        if len(prompt) + max_new_tokens > limit:
            raise ValueError(
                f"prompt {len(prompt)} + {max_new_tokens} exceeds "
                f"max_len {self.model.max_len}"
                + (f" - gamma {self.gamma} (speculative lookahead)"
                   if self.gamma else ""))
        req = _Request(prompt, max_new_tokens, eos_id, prefix=prefix,
                       deadline=deadline)
        with self._submit_lock:
            if self.paged:
                worst = self._worst_pages(len(prompt), int(max_new_tokens),
                                          shared_pages)
                # own prefix included in the ceiling — _worst_pages
                # already credits the own prefix's shared count; pages
                # held by other prefixes never return to _avail, so a
                # request that only fits without them would sit at the
                # FIFO head forever, wedging everyone behind it
                ceiling = self._page_ceiling()
                if worst > ceiling:
                    raise ValueError(
                        f"request needs up to {worst} pages but only "
                        f"{ceiling} of the pool's {self._np - 1} can ever "
                        "free up (registered prefixes hold the rest); "
                        "raise num_pages or release prefixes")
            if self._stopped:
                # a late submit racing stop() would otherwise wait forever
                # on a stream nobody will ever close
                raise RuntimeError("ContinuousBatcher is stopped")
            if prefix is not None:
                if prefix not in self._prefixes:  # released since lookup
                    raise ValueError(f"prefix {prefix} was released")
                self._prefixes[prefix]["refs"] += 1
            self._intake.put(req)
        return req.stream

    def stream_text(self, tokenizer, text: str,
                    max_new_tokens: int = 32) -> Iterator[str]:
        """serving.stream_reply-ready: text in, decoded word chunks out.

        Ids buffer until a token COMPLETES a word (tokenizer.is_word_end:
        its vocab string carries the end-of-word marker, or eos), then the
        whole word decodes as one piece — a word split across BPE subword
        tokens must never stream with spaces inside it.  Tokenizers
        without the concept degrade to per-token emission."""
        ids = tokenizer.encode(text, append_eos=False)
        word_end = getattr(tokenizer, "is_word_end", lambda _t: True)
        buf: List[int] = []
        for tok in self.submit(ids, max_new_tokens,
                               eos_id=tokenizer.eos_id):
            buf.append(tok)
            if word_end(tok):
                piece = tokenizer.decode(buf)
                buf.clear()
                if piece:
                    yield piece + " "
        if buf:  # stream ended mid-word (max_new_tokens hit)
            piece = tokenizer.decode(buf)
            if piece:
                yield piece + " "

    # ---- scheduler loop ------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        # under _submit_lock: _ctl_call's inline path decides "no loop
        # thread is running" and mutates pool state under this lock — the
        # spawn must not interleave with that decision
        with self._submit_lock:
            self._running.set()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="continuous-batcher")
            self._thread.start()
        return self

    def stop(self):
        with self._submit_lock:
            # after this block no submit() can enqueue, so the drain
            # below is complete
            self._stopped = True
        self._running.clear()
        if self._thread is not None:
            # the drain below treats _buffer/_live as single-owner, so the
            # loop thread must actually be DEAD first — one tick can
            # legitimately take tens of seconds (first XLA compile of a
            # new prefill bucket over a tunneled chip), so keep joining
            # well past that before declaring the loop wedged
            deadline = 300.0
            while self._thread.is_alive() and deadline > 0:
                self._thread.join(timeout=10)
                deadline -= 10
            if self._thread.is_alive():
                raise RuntimeError(
                    "ContinuousBatcher loop thread failed to exit within "
                    "300s; refusing to drain its queues concurrently")
        # unblock any consumers still waiting on admitted streams
        for req in self._live:
            if req is not None:
                req.stream._q.put(None)
        # loop thread is dead; the intake (buffer + pending) is ours now —
        # the runtime's one graceful-drain path settles every stream
        self._intake.drain_all(lambda req: req.stream._q.put(None))
        while True:  # unblock any caller waiting on a control op
            try:
                rec = self._ctl.get_nowait()
            except Empty:
                break
            rec["error"] = RuntimeError("ContinuousBatcher is stopped")
            rec["event"].set()

    def _bucket(self, n: int) -> int:
        """Power-of-two prompt bucket so admission compiles O(log
        max_len) prefill shapes total instead of one per distinct length
        (seconds-long XLA stalls in the serving hot path).  The padded
        tail is sound: causal masking keeps positions < n exact, and
        the garbage K/V rows >= n are never attendable — a decode step
        at pos p masks rows > p and overwrites row p itself first."""
        b = 16
        while b < n:
            b *= 2
        b = min(b, self.model.max_len)
        if self.draft_model is not None:
            b = min(b, self.draft_model.max_len)
        return b

    def _admit_batch(self, batch):
        """Admit several (slot, request) pairs with ONE prefill forward
        per prompt bucket: a burst of arrivals costs one device program
        instead of one per request.  Row counts pad to powers of two
        (capped at max_slots) so each bucket compiles O(log max_slots)
        batch shapes; pad rows compute garbage that the slot-indexed
        loads drop (out-of-range sentinel + mode='drop')."""
        from ..models.generation import _prefill_cache

        now = time.monotonic()
        for slot, req in batch:
            # slot-wait span on the SUBMITTER's trace (cross-thread hop)
            if req.trace is not None:
                telemetry.record_span("serving.batcher.admit", req.trace,
                                      now - req.submitted_at, slot=slot)
        by_bucket: dict = {}
        prefix_groups: dict = {}
        for slot, req in batch:
            if req.prefix is not None:
                rec = self._prefixes[req.prefix]
                rest = len(req.prompt) - rec["shared"] * self.page_size
                rb = self._bucket(max(rest, 1)) if rest else 0
                prefix_groups.setdefault(rb, []).append((slot, req))
            else:
                by_bucket.setdefault(self._bucket(len(req.prompt)),
                                     []).append((slot, req))
        if prefix_groups:
            self._admit_prefix_groups(prefix_groups)

        def pack_bucket(item):
            # host-side prompt packing for one bucket; runs on the input
            # pipeline so bucket i+1 packs while bucket i's prefill
            # forward occupies the device
            b, group = item
            kb = len(group)
            kp = 1
            while kp < kb:
                kp *= 2
            kp = min(kp, self.max_slots)
            padded = np.zeros((kp, b), np.int32)
            slots = np.full(kp, self.max_slots, np.int32)  # OOB = dropped
            for i, (slot, req) in enumerate(group):
                padded[i, :len(req.prompt)] = req.prompt
                slots[i] = slot
            return group, kp, padded, slots

        buckets = sorted(by_bucket.items())
        if len(buckets) > 1:
            packed = FlowGraph([PrefillStage(fn=pack_bucket)],
                               label="prefill").run(buckets)
        else:  # one bucket: nothing to overlap, skip the worker thread
            packed = map(pack_bucket, buckets)
        for group, kp, padded, slots in packed:
            k = len(group)
            # the upload rides the feed engine: counted bytes, transfer
            # spans on the request trace, the feed.device_put fault point
            d_padded = self._feed.put(padded)
            logits, cache = _prefill_cache(self.model, self.variables,
                                           d_padded,
                                           self.kv_cache_dtype)
            if self.draft_model is not None:
                # the draft's cache must hold the same prompt history;
                # its prefill logits are unused — the first pending token
                # is the TARGET's (exactness requires it)
                _dlg, d_rows = _prefill_cache(self.draft_model,
                                              self.draft_variables,
                                              d_padded)
                self._d_cache = self._load_many(self._d_cache, d_rows,
                                                jnp.asarray(slots))
            if self.paged:
                # allocate each slot's prompt pages and scatter all rows'
                # prefill pages in one update; bucketing garbage inside
                # the last page is masked/overwritten as in dense
                ids = np.full((kp, self._mp), self._np, np.int32)
                for i, (slot, req) in enumerate(group):
                    need = -(-len(req.prompt) // self.page_size)
                    pages = [self._free.pop() for _ in range(need)]
                    self._slot_pages[slot] = pages
                    self._slot_shared[slot] = 0
                    self._table[slot].fill(0)
                    self._table[slot, :need] = pages
                    ids[i, :need] = pages
                self._cache = self._load_paged_many(
                    self._cache, cache, jnp.asarray(ids.reshape(-1)))
            else:
                self._cache = self._load_many(self._cache, cache,
                                              jnp.asarray(slots))
            firsts = np.asarray(jnp.argmax(logits[
                jnp.arange(kp), jnp.asarray(
                    [len(r.prompt) - 1 for _s, r in group]
                    + [0] * (kp - k))], axis=-1), np.int32)
            for i, (slot, req) in enumerate(group):
                self._live[slot] = req
                self._pos[slot] = len(req.prompt)
                self._tok[slot] = int(firsts[i])
                self._emit(slot, int(firsts[i]))

    def _admit_prefix_groups(self, prefix_groups):
        """Admit shared-prefix requests: wire each slot's page table to
        the prefix's read-only pages + freshly allocated own pages, then
        prefill ONLY the suffix via one slot-BLOCK decode per rest
        bucket (the block attends the shared rows through the table —
        exactly the full prefill's math for those positions).  rest=0
        requests skip the forward entirely: their first token comes from
        the logits the prefix registration stored."""
        from ..models.generation import _prefill_cache

        if self.draft_model is not None:
            # the dense draft cache cannot share pages — prefill the FULL
            # prompts, batched per bucket like _admit_batch (the draft is
            # the cheap model; the TARGET's prefix reuse is the win)
            by_draft_bucket: dict = {}
            for group in prefix_groups.values():
                for slot, req in group:
                    by_draft_bucket.setdefault(
                        self._bucket(len(req.prompt)), []).append((slot, req))
            for db, dgroup in sorted(by_draft_bucket.items()):
                dk = len(dgroup)
                dkp = 1
                while dkp < dk:
                    dkp *= 2
                dkp = min(dkp, self.max_slots)
                dpad = np.zeros((dkp, db), np.int32)
                dslots = np.full(dkp, self.max_slots, np.int32)
                for i, (slot, req) in enumerate(dgroup):
                    dpad[i, :len(req.prompt)] = req.prompt
                    dslots[i] = slot
                _dl, d_rows = _prefill_cache(self.draft_model,
                                             self.draft_variables,
                                             jnp.asarray(dpad))
                self._d_cache = self._load_many(self._d_cache, d_rows,
                                                jnp.asarray(dslots))
        for rb, group in sorted(prefix_groups.items()):
            fill = []                  # rows that need a suffix forward
            for slot, req in group:
                rec = self._prefixes[req.prefix]
                shared = rec["shared"]
                shared_tokens = shared * self.page_size
                n = len(req.prompt)
                need = -(-n // self.page_size) - shared
                pages = [self._free.pop() for _ in range(need)]
                self._slot_pages[slot] = pages
                self._slot_shared[slot] = shared
                self._table[slot].fill(0)
                self._table[slot, :shared] = rec["pages"]
                self._table[slot, shared:shared + need] = pages
                if n > shared_tokens:
                    fill.append((slot, req, shared_tokens))
                else:
                    first = int(np.argmax(rec["last_logits"]))
                    self._live[slot] = req
                    self._pos[slot] = n
                    self._tok[slot] = first
                    self._emit(slot, first)
            if not fill:
                continue
            k = len(fill)
            kp = 1
            while kp < k:
                kp *= 2
            kp = min(kp, self.max_slots)
            toks = np.zeros((kp, rb), np.int32)
            pos = np.zeros(kp, np.int32)
            tables = np.zeros((kp, self._mp), np.int32)
            for i, (slot, req, st) in enumerate(fill):
                toks[i, :len(req.prompt) - st] = req.prompt[st:]
                pos[i] = st
                tables[i] = self._table[slot]
            d_toks, d_fpos, d_tbls = self._feed.put_group(
                [toks, pos, tables])
            logits, self._cache = self._step(
                self.variables, d_toks, self._cache, d_fpos, d_tbls)
            firsts = np.asarray(jnp.argmax(logits[
                jnp.arange(kp), jnp.asarray(
                    [len(r.prompt) - st - 1 for _s, r, st in fill]
                    + [0] * (kp - k))], axis=-1), np.int32)
            for i, (slot, req, _st) in enumerate(fill):
                self._live[slot] = req
                self._pos[slot] = len(req.prompt)
                self._tok[slot] = int(firsts[i])
                self._emit(slot, int(firsts[i]))

    def _emit(self, slot: int, tok: int):
        req = self._live[slot]
        req.emitted += 1
        req.stream._q.put(tok)
        done = (req.emitted >= req.max_new
                or (req.eos_id is not None and tok == req.eos_id)
                or int(self._pos[slot]) + 1 >= self.model.max_len)
        if done:
            req.stream._q.put(None)
            self._live[slot] = None
            # park the freed slot at position 0: a slot that finished
            # near max_len must not leave a stale pos that speculative
            # lookahead (pos + gamma) could push past the cache bound
            self._pos[slot] = 0
            self._tok[slot] = 0
            if self.paged:  # return OWNED pages + release the reservation
                self._free.extend(self._slot_pages[slot])
                self._slot_pages[slot] = []
                self._slot_shared[slot] = 0
                self._table[slot].fill(0)
                self._avail += self._slot_reserved[slot]
                self._slot_reserved[slot] = 0
                if req.prefix is not None:
                    with self._submit_lock:
                        self._prefixes[req.prefix]["refs"] -= 1

    def _drain_intake(self):
        while True:  # control ops first: admissions may depend on them
            try:
                rec = self._ctl.get_nowait()
            except Empty:
                break
            try:
                rec["result"] = rec["op"](rec["payload"])
            except Exception as e:  # noqa: BLE001 — surfaced to the caller
                rec["error"] = e
            rec["event"].set()
        self._intake.drain_to_buffer()

    def _try_admit(self):
        """Admit from the FIFO head into free slots — collected into ONE
        batched prefill (_admit_batch).  Paged mode admits only while
        the head's worst-case page reservation fits the unreserved
        budget — strict FIFO (no skipping), so a big request can't be
        starved by a stream of small ones."""
        if self.paged:
            # fail-fast pre-pass: a prefix registered AFTER a request
            # passed submit()'s ceiling check can shrink the achievable
            # budget below its reservation — a head that can NEVER fit
            # must error its stream, not wedge the FIFO forever
            ceiling = self._page_ceiling()
            while self._buffer:
                head = self._buffer[0]
                shared = (self._prefixes[head.prefix]["shared"]
                          if head.prefix is not None else 0)
                if self._worst_pages(len(head.prompt), head.max_new,
                                     shared) <= ceiling:
                    break
                self._buffer.popleft()
                if head.prefix is not None:
                    with self._submit_lock:
                        self._prefixes[head.prefix]["refs"] -= 1
                head.stream.error = RuntimeError(
                    "request dropped: its worst-case page reservation "
                    f"exceeds the {ceiling} pages that can ever free up "
                    "(prefixes registered after submit hold the rest)")
                head.stream._q.put(None)
        if any(r.deadline is not None for r in self._buffer):
            # fail-fast: an expired request must not consume a prefill —
            # its client has already given up (deadline semantics match
            # WorkerServer._admit; docs/robustness.md).  The reap itself
            # is the AdmissionStage's one code path.
            def _expire(req: _Request):
                if req.prefix is not None:
                    with self._submit_lock:
                        self._prefixes[req.prefix]["refs"] -= 1
                req.stream.error = TimeoutError(
                    "request deadline expired before batch admission")
                req.stream._q.put(None)

            self._intake.reap_expired(lambda r: r.deadline, _expire)
        batch = []
        for slot in range(self.max_slots):
            if not self._buffer:
                break
            if self._live[slot] is not None:
                continue
            req = self._buffer[0]
            if self.paged:
                shared = (self._prefixes[req.prefix]["shared"]
                          if req.prefix is not None else 0)
                worst = self._worst_pages(len(req.prompt), req.max_new,
                                          shared)
                if worst > self._avail:
                    break
                self._avail -= worst
                self._slot_reserved[slot] = worst
            self._buffer.popleft()
            batch.append((slot, req))  # each slot index visited once
        if batch:
            self._admit_batch(batch)

    def _loop(self):
        while self._running.is_set():
            self._drain_intake()
            self._try_admit()
            active = [s for s in range(self.max_slots)
                      if self._live[s] is not None]
            if not active:
                if not self._buffer:
                    try:
                        self._buffer.append(
                            self._intake.get(timeout=self.idle_sleep_s))
                    except Empty:
                        continue
                # nothing live -> every reservation is released, so the
                # head always fits; the next iteration admits it
                continue
            telemetry.histogram("serving.batcher.batch_fill").observe(
                len(active) / self.max_slots)
            if self.paged:
                # grow each active slot's page list just-in-time for this
                # tick's write positions — speculative mode writes up to
                # pos + gamma (the admission reservation guarantees the
                # free list can cover it)
                for sl in active:
                    idx = (int(self._pos[sl]) + self.gamma) // self.page_size
                    while idx >= (self._slot_shared[sl]
                                  + len(self._slot_pages[sl])):
                        pg = self._free.pop()
                        self._table[sl, self._slot_shared[sl]
                                    + len(self._slot_pages[sl])] = pg
                        self._slot_pages[sl].append(pg)
            if self.draft_model is not None:
                self._speculative_tick(active)
                continue
            # ONE batched step for every slot (free slots compute too —
            # their pos 0 writes are dead: dense mode overwrites the rows
            # on admit, paged mode routes them to the trash page), fed by
            # ONE packed upload of this tick's tok/pos(/table) vectors
            if self.paged:
                d_tok, d_pos, d_tbl = self._feed.put_group(
                    [self._tok[:, None], self._pos, self._table])
            else:
                d_tok, d_pos = self._feed.put_group(
                    [self._tok[:, None], self._pos])
                d_tbl = None
            lg, self._cache = self._step(
                self.variables, d_tok, self._cache, d_pos, d_tbl)
            nxt = np.asarray(jnp.argmax(lg[:, 0], axis=-1), np.int32)
            for slot in active:
                self._pos[slot] += 1
                self._tok[slot] = nxt[slot]
                self._emit(slot, int(nxt[slot]))

    def _speculative_tick(self, active):
        """One speculative round for ALL slots: (gamma+1) draft slot
        steps propose, ONE target slot-block step verifies, each slot
        emits its accepted prefix + the target's own next token — the
        per-slot speculative-decoding recurrence (speculative_generate's
        round, vectorized over co-tenant slots).  The +1 extra draft
        step writes the would-be-next K/V row so a fully-accepted round
        leaves no hole in the draft cache."""
        g = self.gamma
        dpos = self._pos.copy()
        # the round's first draft step is the only one that uploads host
        # data (later steps chain device outputs): tok+pos ride one
        # packed transfer; per-step position bumps re-upload through the
        # feed so the telemetry sees every byte on the wire
        d_tok, d_pos = self._feed.put_group([self._tok[:, None], dpos])
        prop_list = []
        for i in range(g + 1):
            lg, self._d_cache = self._d_step(
                self.draft_variables, d_tok, self._d_cache, d_pos)
            nxt = jnp.argmax(lg[:, 0], axis=-1).astype(jnp.int32)
            if i < g:
                # keep proposals ON DEVICE: a host sync here would block
                # async dispatch of the next draft step
                prop_list.append(nxt)
            d_tok = nxt[:, None]
            dpos += 1
            if i < g:
                d_pos = self._feed.put(dpos)
        props = np.asarray(jnp.stack(prop_list, axis=1), np.int32)  # [S, g]
        # ONE target forward verifies every slot's pending token + its g
        # proposals at the slot's own position: logits[:, j] predicts
        # position pos+j+1
        block = np.concatenate([self._tok[:, None], props], axis=1)
        if self.paged:
            d_blk, d_vpos, d_tbl = self._feed.put_group(
                [block, self._pos, self._table])
        else:
            d_blk, d_vpos = self._feed.put_group([block, self._pos])
            d_tbl = None
        lg, self._cache = self._step(
            self.variables, d_blk, self._cache, d_vpos, d_tbl)
        t_pred = np.asarray(jnp.argmax(lg, axis=-1), np.int32)  # [S, g+1]
        for slot in active:
            match = t_pred[slot, :g] == props[slot]
            m = int(np.argmin(np.concatenate(
                [match, np.zeros(1, bool)])))                   # 0..g
            for j in range(m + 1):
                tok = int(props[slot, j]) if j < m else int(t_pred[slot, m])
                self._pos[slot] += 1
                self._tok[slot] = tok
                self._emit(slot, tok)
                if self._live[slot] is None:
                    break  # finished mid-block: discard the rest
