"""Fleet control plane, capacity half: the autoscale signal bus.

The ROADMAP's planet-scale item asks for "replica counts driven from the
existing serving.batcher.queue/latency telemetry (autoscale hook next to
the RolloutController)" — this is that hook.  It closes the loop from
the PR 15 federated telemetry plane back into the PR 9 pool:

* :class:`CapacityModel` is pure math: fold the merged fleet view
  (queue depth, batch fill, SLO burn rate, per-replica HBM headroom)
  plus the live pool shape into one replica-count recommendation with
  stated reasons.  No sockets, no threads — unit-testable on dict
  fixtures.
* :class:`AutoscaleController` consumes recommendations next to the
  RolloutController: scale-UP provisions replicas through an injected
  ``provisioner(count)`` callback (the operator owns process creation —
  k8s, subprocess pool, in-process servers in tests); scale-DOWN reuses
  the rollout's :func:`~mmlspark_tpu.serving.rollout.drain_and_stop`
  graceful drain, so no accepted request is dropped by a scale event.
  Hysteresis (N consecutive agreeing recommendations) plus a cooldown
  clock keep canary traffic shifts and probe flaps from flapping the
  pool size.  It also garbage-collects replicas that stayed dead past a
  grace window — removing them from the registered set is what lets an
  availability alert RESOLVE once replacements are live.

Clock-injectable (`utils.faults.monotonic`) so cooldown/hysteresis are
testable under a VirtualClock; operator story in docs/serving.md and
docs/observability.md.
"""
from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, List, Mapping, Optional

from ..core import telemetry
from ..core.telemetry.fleet import hist_total
from ..utils.faults import monotonic as _monotonic
from ..utils.sync import make_lock
from .fleet import FleetGateway, Replica
from .rollout import drain_and_stop

__all__ = ["CapacityModel", "AutoscaleController"]

# merged-view gauge names folded into the queue-pressure signal
_QUEUE_GAUGES = ("serving.queue.depth", "serving.batcher.queue_depth")
_FILL_HISTS = ("serving.batch.fill", "serving.batcher.batch_fill")


class CapacityModel:
    """Replica-count recommendation from the merged fleet view.

    Signals, strongest first:

    * **burn** — an SLO alert pending/firing means the fleet is eating
      error budget NOW: recommend at least the registered count
      (replace whatever died) plus one when the burn is not an
      availability gap (latency/deadline burn needs more capacity, not
      just replacement).
    * **queue** — total queued work / `target_queue_per_replica` is the
      steady-state demand floor.
    * **fill** — median batch fill above `fill_high` means batches are
      packing full (capacity bound); below `fill_low` the pool is
      padding batches (over-provisioned).
    * **HBM headroom** — with a configured `hbm_limit_bytes`, a replica
      whose in-use bytes leave less than `hbm_headroom_frac` headroom
      argues one replica up (spillover room before OOM).

    Scale-down is deliberately timid: only when NO pressure signal is
    up does the model step down, one replica at a time.
    """

    def __init__(self,
                 target_queue_per_replica: float = 8.0,
                 fill_high: float = 0.85,
                 fill_low: float = 0.30,
                 hbm_limit_bytes: Optional[float] = None,
                 hbm_headroom_frac: float = 0.10,
                 min_replicas: int = 1,
                 max_replicas: int = 8):
        self.target_queue_per_replica = float(target_queue_per_replica)
        self.fill_high = float(fill_high)
        self.fill_low = float(fill_low)
        self.hbm_limit_bytes = hbm_limit_bytes
        self.hbm_headroom_frac = float(hbm_headroom_frac)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)

    # ---- signal extraction ---------------------------------------------

    @staticmethod
    def _total_queue(merged: Mapping[str, Any]) -> float:
        g = merged.get("gauges") or {}
        total = 0.0
        for name in _QUEUE_GAUGES:
            for rkey, v in (g.get(name) or {}).items():
                if rkey != "gateway":
                    total += float(v)
        return total

    @staticmethod
    def _fill_p50(merged: Mapping[str, Any]) -> Optional[float]:
        parts = [hist_total(merged, name) for name in _FILL_HISTS]
        parts = [p for p in parts if p["count"] > 0]
        if not parts:
            return None
        # take the busiest fill family (server-level vs batcher-level)
        best = max(parts, key=lambda p: p["count"])
        return best.get("p50")

    def _hbm_pressure(self, merged: Mapping[str, Any]) -> bool:
        if not self.hbm_limit_bytes:
            return False
        per = (merged.get("gauges") or {}).get(
            "device.hbm.bytes_in_use") or {}
        for rkey, used in per.items():
            if rkey == "gateway":
                continue
            headroom = 1.0 - float(used) / float(self.hbm_limit_bytes)
            if headroom < self.hbm_headroom_frac:
                return True
        return False

    # ---- the recommendation --------------------------------------------

    def recommend(self, merged: Mapping[str, Any],
                  alerts: List[Mapping[str, Any]],
                  n_routable: int, n_registered: int) -> Dict[str, Any]:
        reasons: List[str] = []
        needs: List[int] = [n_routable]

        hot = [a for a in alerts if a.get("state") in ("pending", "firing")]
        if hot:
            worst = max(hot, key=lambda a: a.get("burn_fast", 0.0))
            if worst["slo"] == "availability":
                # replicas died: restore to the registered strength
                need = max(n_registered, n_routable + 1)
                reasons.append(
                    f"availability burn {worst.get('burn_fast')}x: "
                    f"replace dead replicas ({n_routable}/{n_registered} "
                    f"routable)")
            else:
                need = n_routable + 1
                reasons.append(f"{worst['slo']} burn "
                               f"{worst.get('burn_fast')}x: add capacity")
            needs.append(need)

        queue = self._total_queue(merged)
        need_q = int(math.ceil(queue / self.target_queue_per_replica)) \
            if queue > 0 else 0
        if need_q > n_routable:
            reasons.append(f"queue depth {queue:g} wants {need_q} replicas")
            needs.append(need_q)

        fill = self._fill_p50(merged)
        if fill is not None and fill >= self.fill_high:
            reasons.append(f"batch fill p50 {fill:.2f} >= "
                           f"{self.fill_high:.2f}")
            needs.append(n_routable + 1)

        if self._hbm_pressure(merged):
            reasons.append("HBM headroom below "
                           f"{self.hbm_headroom_frac:.0%}")
            needs.append(n_routable + 1)

        target = max(needs)
        if target <= n_routable and not reasons:
            # scale-down path: every pressure signal quiet AND fill low
            idle = (fill is None or fill <= self.fill_low) and \
                need_q < n_routable and queue == 0.0
            if idle and n_routable > self.min_replicas:
                target = n_routable - 1
                reasons.append(
                    "no pressure: queue empty"
                    + (f", fill p50 {fill:.2f}" if fill is not None else ""))
        target = max(self.min_replicas, min(self.max_replicas, target))
        return {
            "target": target,
            "routable": n_routable,
            "registered": n_registered,
            "reasons": reasons,
            "inputs": {"queue": queue, "fill_p50": fill,
                       "alerts": {a["slo"]: a["state"] for a in alerts}},
        }


class AutoscaleController:
    """Act on CapacityModel recommendations against a live gateway.

    ``evaluate_once()`` is the unit of control (tests call it directly;
    ``run(poll_s)`` steps it on a daemon thread).  One evaluation:

    1. garbage-collect replicas dead past `dead_grace_s` (unroutable,
       unhealthy, never recovered) — shrinking the registered set so an
       availability alert can resolve once replacements carry traffic;
    2. read the telemetry plane's merged view + alert states;
    3. fold through the model; publish ``autoscale.target_replicas``;
    4. act only when `hysteresis` consecutive recommendations agree on
       the direction AND the cooldown has elapsed: scale-up through the
       provisioner callback, scale-down through the shared rollout
       drain.
    """

    def __init__(self, gateway: FleetGateway,
                 provisioner: Optional[Callable[[int], int]] = None,
                 model: Optional[CapacityModel] = None,
                 cooldown_s: float = 10.0,
                 hysteresis: int = 2,
                 drain_timeout_s: float = 5.0,
                 dead_grace_s: float = 1.0,
                 clock: Callable[[], float] = _monotonic):
        self.gateway = gateway
        self.provisioner = provisioner
        self.model = model or CapacityModel()
        self.cooldown_s = float(cooldown_s)
        self.hysteresis = max(1, int(hysteresis))
        self.drain_timeout_s = float(drain_timeout_s)
        self.dead_grace_s = float(dead_grace_s)
        self._clock = clock
        self._lock = make_lock("serving.fleet.autoscale")
        self._dead_since: Dict[str, float] = {}  #: guarded-by self._lock
        self._pending_dir: List[int] = []  #: guarded-by self._lock
        self._last_action = -math.inf  #: guarded-by self._lock
        self.last: Optional[Dict[str, Any]] = None
        self.history: List[Dict[str, Any]] = []
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        gateway.autoscale = self

    # ---- dead-replica GC -----------------------------------------------

    def _gc_dead(self, now: float) -> List[str]:
        removed: List[str] = []
        reps = self.gateway.replicas()
        live = {r.key for r in reps}
        with self._lock:
            for k in [k for k in self._dead_since if k not in live]:
                del self._dead_since[k]
            for rep in reps:
                if rep.healthy or rep.draining:
                    self._dead_since.pop(rep.key, None)
                    continue
                since = self._dead_since.setdefault(rep.key, now)
                if now - since >= self.dead_grace_s:
                    removed.append(rep.key)
        for key in removed:
            self.gateway.remove_replica(key)
            with self._lock:
                self._dead_since.pop(key, None)
        return removed

    # ---- the control step ----------------------------------------------

    def evaluate_once(self) -> Dict[str, Any]:
        now = self._clock()
        removed = self._gc_dead(now)
        plane = self.gateway.telemetry_plane
        merged = plane.merged()
        if merged is None:
            merged = plane.ensure_fresh()
        alerts = plane.engine.alerts()
        reps = self.gateway.replicas()
        n_routable = sum(1 for r in reps if r.routable())
        n_registered = len(reps)
        rec = self.model.recommend(merged, alerts, n_routable,
                                   n_registered)
        telemetry.gauge("autoscale.target_replicas").set(rec["target"])
        direction = (1 if rec["target"] > n_routable
                     else -1 if rec["target"] < n_routable else 0)
        with self._lock:
            self._pending_dir.append(direction)
            del self._pending_dir[:-self.hysteresis]
            agreed = (direction != 0
                      and len(self._pending_dir) >= self.hysteresis
                      and all(d == direction for d in self._pending_dir))
            cooled = now - self._last_action >= self.cooldown_s
        action = "none"
        if agreed and cooled:
            if direction > 0:
                added = self._scale_up(rec["target"] - n_routable)
                action = f"up+{added}" if added else "up_failed"
            else:
                action = "down-1" if self._scale_down() else "down_failed"
            with self._lock:
                self._last_action = now
                self._pending_dir.clear()
        rec = dict(rec, action=action, gc_removed=removed, t=now)
        self.last = rec
        self.history.append(rec)
        del self.history[:-64]
        return rec

    def _scale_up(self, count: int) -> int:
        if self.provisioner is None:
            return 0
        try:
            added = int(self.provisioner(count) or 0)
        except Exception:  # noqa: BLE001 — a broken provisioner must not
            added = 0      # kill the control loop
        if added > 0:
            telemetry.incr("autoscale.up", added)
        return added

    def _scale_down(self) -> bool:
        """Drain the least-loaded routable replica (never below the
        model floor — recommend() already enforced it)."""
        pool = [r for r in self.gateway.replicas() if r.routable()]
        if len(pool) <= self.model.min_replicas:
            return False
        victim = min(pool, key=lambda r: r.inflight)
        drain_and_stop(self.gateway, victim, self.drain_timeout_s)
        self.gateway.remove_replica(victim.key)
        telemetry.incr("autoscale.down")
        return True

    # ---- lifecycle -----------------------------------------------------

    def run(self, poll_s: float = 0.5) -> threading.Thread:
        self._stop_evt.clear()
        def _loop():
            while not self._stop_evt.wait(poll_s):
                try:
                    self.evaluate_once()
                except Exception:  # noqa: BLE001 — control loop survives
                    pass
        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="fleet-autoscale")
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def describe(self) -> dict:
        return {
            "cooldown_s": self.cooldown_s,
            "hysteresis": self.hysteresis,
            "min_replicas": self.model.min_replicas,
            "max_replicas": self.model.max_replicas,
            "last": self.last,
            "history": self.history[-8:],
        }
