"""mmlspark_tpu — a TPU-native ML pipeline framework with the capabilities of
MMLSpark (mhamilton723/mmlspark): Estimator/Transformer pipelines over columnar
tables, deep-learning batch inference + transfer learning on JAX/pjit, fused
Pallas image preprocessing, distributed GBDT and hashed online learners with
XLA-collective AllReduce, low-latency serving, explainers, and analytics.
"""
from .version import __version__
from .core.schema import Table, CategoricalMap, find_unused_column_name
from .core.params import Param, ComplexParam, ServiceParam, Params, TypeConverters
from .core.pipeline import (
    PipelineStage,
    Transformer,
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    LambdaTransformer,
    ml_transform,
)
from .core import registry

__all__ = [
    "__version__",
    "Table",
    "CategoricalMap",
    "find_unused_column_name",
    "Param",
    "ComplexParam",
    "ServiceParam",
    "Params",
    "TypeConverters",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "LambdaTransformer",
    "ml_transform",
    "registry",
]
