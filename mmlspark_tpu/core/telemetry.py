"""Usage telemetry: every stage verb logs a structured JSON record.

Reference: core logging/BasicLogging.scala:25-71 — logClass/logFit/logTransform
emit `{uid, className, method, buildVersion}`.  Here: a process-local ring
buffer + stdlib logging, cheap enough to stay always-on, with wall-time capture
(also covering stages/Timer.scala:55 TimerModel semantics).

Also the process-wide **event counter** sink: every fault/retry/shed/
degrade event in the resilience layer (io/feed retries and degradations,
serving load shedding and deadline expiries, circuit-breaker transitions,
training auto-checkpoint/resume, injected faults) increments a named
counter here, so chaos runs and production incidents read off one ledger
(`counters()` / `reset_counters()`); see docs/robustness.md.
"""
from __future__ import annotations

import collections
import contextlib
import json
import logging
import threading
import time
from typing import Any, Deque, Dict, Optional

from .. import version

logger = logging.getLogger("mmlspark_tpu.telemetry")

_RECORDS: Deque[Dict[str, Any]] = collections.deque(maxlen=4096)

_COUNTERS: Dict[str, int] = {}
_COUNTERS_LOCK = threading.Lock()


def incr(name: str, n: int = 1) -> None:
    """Bump a named event counter (dotted names: 'serving.shed')."""
    with _COUNTERS_LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def counters(prefix: Optional[str] = None) -> Dict[str, int]:
    """Snapshot the event counters, optionally filtered by name prefix."""
    with _COUNTERS_LOCK:
        if prefix is None:
            return dict(_COUNTERS)
        return {k: v for k, v in _COUNTERS.items() if k.startswith(prefix)}


def reset_counters(prefix: Optional[str] = None) -> None:
    """Zero the counters (tests); with `prefix`, only matching names."""
    with _COUNTERS_LOCK:
        if prefix is None:
            _COUNTERS.clear()
        else:
            for k in [k for k in _COUNTERS if k.startswith(prefix)]:
                del _COUNTERS[k]


def recent_records():
    return list(_RECORDS)


def clear_records():
    _RECORDS.clear()


@contextlib.contextmanager
def log_verb(stage, method: str):
    t0 = time.perf_counter()
    err = None
    try:
        yield
    except Exception as e:  # noqa: BLE001 — record then re-raise
        err = type(e).__name__
        raise
    finally:
        rec = {
            "uid": getattr(stage, "uid", "?"),
            "className": type(stage).__name__,
            "method": method,
            "buildVersion": version.__version__,
            "wallTimeSec": round(time.perf_counter() - t0, 6),
        }
        if err:
            rec["error"] = err
        _RECORDS.append(rec)
        logger.debug("%s", json.dumps(rec))


class StopWatch:
    """ns-resolution accumulating timer (core/utils/StopWatch.scala:6)."""

    def __init__(self):
        self.elapsed_ns = 0
        self._start = None

    def start(self):
        self._start = time.perf_counter_ns()

    def stop(self):
        if self._start is not None:
            self.elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    @contextlib.contextmanager
    def measure(self):
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def elapsed_sec(self) -> float:
        return self.elapsed_ns / 1e9
