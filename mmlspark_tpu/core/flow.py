"""graftflow: the credit-based staged-dataflow runtime.

DeviceFeed, HostPipeline, and the ContinuousBatcher each grew their own
bounded queues, backpressure rules, degradation ladders, and telemetry
conventions across PRs 2/4/7 — so chaos coverage and overload semantics
differed per path.  This module is the one scheduler they now share
(ROADMAP: "unify the three engines behind one scheduler"), built so that
uniform *failure* semantics fall out of the structure:

  * **Stages with credit budgets.**  A `FlowGraph` is an ordered list of
    `Stage`s, each with a named worker pool and a bounded CREDIT budget
    instead of an ad-hoc `Queue(maxsize=...)`.  An upstream hop acquires
    one of the downstream stage's credits before enqueueing and the
    credit is released only when the item is handed onward — so a
    stage's budget bounds its queued + in-worker + reorder-parked items
    together.  Backpressure is the credit wait: a slow stage stalls its
    producer, memory stays O(credits x item), never O(stream).
  * **Order-restoring emission.**  Workers finish out of order; a
    per-stage reorder buffer re-emits in sequence (the same contract
    HostPipeline pinned in PR 7 — the DeviceFeed coalescer depends on
    same-shape runs staying adjacent).
  * **One deadline model.**  Items carry an absolute monotonic deadline
    (propagated from the serving `X-Deadline-Ms` header via
    `deadline_from_ms`).  A budget that lapses mid-graph sheds at the
    NEXT stage boundary: the item's slot becomes an `Expired` marker
    that keeps riding the reorder buffers (ordering is never lost) while
    no further stage fn runs on it.  Serving maps markers to 504, io
    paths skip them — `run(yield_expired=...)` picks the semantics.
  * **Chaos-injectable everywhere.**  Every stage auto-registers a
    `flow.<stage>` fault point at graph construction
    (`flow_fault_points()` lists them; `tools/chaos_soak.py --flow` arms
    seeded faults at every one).  A `StagePolicy` gives a stage the
    retry-then-degrade ladder DeviceFeed pioneered, with backoff sleeps
    through the injectable clock (utils/faults.py) so chaos runs resolve
    in milliseconds.
  * **Declared telemetry on every queue.**  Depths mirror to
    `flow.queue.depth.<stage>` gauges, sheds/expiries count into
    `flow.shed[.<stage>]` / `flow.expired[.<stage>]`, per-item work into
    `flow.items.<stage>` and the `flow.stage.latency{stage=}` histogram;
    worker threads attach `<span_prefix>.<stage>` spans to the trace
    active where the graph was started (the cross-thread hop
    record_span exists for).  Lint rule G405 holds every registered
    `Stage` subclass to a bounded class-level credit budget and declared
    `flow.<name>.*` metric rows.

Failure semantics are HostPipeline's, now uniform: a stage or producer
exception cancels the graph and the consumer re-raises the ORIGINAL
error; all waits are cancel-aware `_POLL_S` loops, so an abandoned
consumer can never strand a worker.  See docs/robustness.md ("The flow
runtime").
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

from ..utils.fault_tolerance import Overloaded
from ..utils.faults import fault_point
from ..utils.faults import monotonic as _clock_monotonic
from ..utils.faults import sleep as _clock_sleep
from ..utils.sync import make_lock
from . import telemetry as core_telemetry

__all__ = ["Stage", "StagePolicy", "FlowGraph", "FlowItem", "Expired",
           "AdmissionStage", "deadline_from_ms", "deadline_expired",
           "flow_fault_points"]

_POLL_S = 0.05  # cancel-aware queue/credit wait quantum

# The runtime sanitizer's observer (tools/graftsan), or None.  Installed
# via set_sanitizer(); every hook site below is a plain attribute read
# plus a None check, priced by bench.py's `sanitizer_overhead_frac`
# contract (< 1% on the per-item flow path when disabled).
_SAN = None


def set_sanitizer(observer) -> None:
    """Install (or, with None, remove) the credit/EOF conservation
    observer.  Called by tools/graftsan install()/uninstall() only."""
    global _SAN
    _SAN = observer


# ---------------------------------------------------------------------------
# Fault-point auto-registration: every queue in the system becomes
# chaos-injectable the moment a graph is built around it.
# ---------------------------------------------------------------------------
_REG_LOCK = make_lock("flow.registry")
_FLOW_FAULT_POINTS: Dict[str, None] = {}  #: guarded-by _REG_LOCK


def _register_fault_point(point: str) -> None:
    with _REG_LOCK:
        _FLOW_FAULT_POINTS.setdefault(point, None)


def flow_fault_points() -> Tuple[str, ...]:
    """Every `flow.<stage>` fault point registered so far, in first-seen
    order — the arming surface `tools/chaos_soak.py --flow` iterates."""
    with _REG_LOCK:
        return tuple(_FLOW_FAULT_POINTS)


# ---------------------------------------------------------------------------
# The deadline model (shared with serving: X-Deadline-Ms -> monotonic).
# ---------------------------------------------------------------------------
def deadline_from_ms(dl_ms) -> Optional[float]:
    """Parse a deadline budget in milliseconds (the `X-Deadline-Ms`
    header value) into an absolute monotonic deadline; malformed or
    missing values mean no deadline — a bad header must not fail a
    request that never asked for a budget."""
    if dl_ms is None:
        return None
    try:
        budget_ms = float(dl_ms)
    except (TypeError, ValueError):
        return None
    return _clock_monotonic() + budget_ms / 1000.0


def deadline_expired(deadline: Optional[float],
                     now: Optional[float] = None) -> bool:
    """True when an absolute monotonic `deadline` has lapsed."""
    if deadline is None:
        return False
    return (_clock_monotonic() if now is None else now) >= deadline


class FlowItem:
    """One item's envelope through the graph: the value plus its
    absolute monotonic deadline (None = no budget)."""

    __slots__ = ("value", "deadline")

    def __init__(self, value: Any, deadline: Optional[float] = None):
        self.value = value
        self.deadline = deadline

    def expired(self) -> bool:
        return deadline_expired(self.deadline)


class Expired:
    """An item whose deadline lapsed mid-graph: it keeps its sequence
    slot through every remaining reorder buffer (ordering is preserved)
    but no further stage fn runs on it.  `stage` names the boundary that
    shed it — the serving layer maps these to 504."""

    __slots__ = ("value", "deadline", "stage")

    def __init__(self, value: Any, deadline: Optional[float], stage: str):
        self.value = value
        self.deadline = deadline
        self.stage = stage


class _EOF:
    """End-of-stream marker carrying the total item count; re-put by the
    worker that pops it so every sibling sees it, forwarded downstream
    by the reorder buffer only after all `total` items emitted.  Rides
    credit-free: credits budget ITEMS, the marker just needs a slot."""

    __slots__ = ("total",)

    def __init__(self, total: int):
        self.total = total


class _Credits:
    """One stage's bounded credit budget: a counting semaphore with
    cancel-aware acquisition.  Holding a credit means the stage is
    accountable for one item — queued, in a worker's hands, or parked in
    its reorder buffer — until it is handed downstream."""

    __slots__ = ("limit", "_sem")

    def __init__(self, limit: int):
        self.limit = max(1, int(limit))
        self._sem = threading.Semaphore(self.limit)

    def acquire(self, cancelled: threading.Event) -> bool:
        """Block for a credit; False when the graph cancelled first."""
        while not cancelled.is_set():
            if self._sem.acquire(timeout=_POLL_S):
                if _SAN is not None:
                    _SAN.on_credit_acquire(self)
                return True
        return False

    def release(self) -> None:
        if _SAN is not None:
            _SAN.on_credit_release(self)
        self._sem.release()


class _Reorder:
    """Order-restoring emitter between a stage's workers and the next
    hop: out-of-order completions park in `pending` until their turn.
    `put` may block on the downstream credit while the lock is held —
    that IS the backpressure (siblings stall on the lock instead of
    racing further ahead); the consumer side never takes this lock, so
    there is no cycle to deadlock on."""

    def __init__(self, put: Callable[[Any], None]):
        self._put = put
        self._lock = make_lock("flow.reorder")
        self._pending: Dict[int, Any] = {}  #: guarded-by self._lock
        self._next = 0  #: guarded-by self._lock
        self._total: Optional[int] = None  #: guarded-by self._lock
        self._eof_sent = False  #: guarded-by self._lock

    def emit(self, seq: int, value: Any):
        with self._lock:
            self._pending[seq] = value
            self._flush()

    def close(self, total: int):
        with self._lock:
            self._total = total
            self._flush()

    def _flush(self):
        while self._next in self._pending:
            self._put((self._next, self._pending.pop(self._next)))
            self._next += 1
        if (self._total is not None and self._next >= self._total
                and not self._eof_sent):
            self._eof_sent = True
            self._put(_EOF(self._total))


class StagePolicy:
    """The retry-then-degrade ladder as a reusable stage policy (the
    shape DeviceFeed._device_put pioneered in PR 2): `retries` total
    attempts, each behind the stage's fault point; a tiny exponential
    backoff between attempts (through the injectable clock, so chaos
    tests cost no wall time); `degrade(value, error)` as the terminal
    rung — when set, exhausted retries fall back instead of raising.
    Injected crashes (`InjectedCrash`, a BaseException) skip the ladder
    entirely: a process death is the supervisor's problem, not a retry's.
    """

    def __init__(self, retries: int = 1, backoff_s: float = 0.001,
                 backoff_cap_s: float = 0.05,
                 retry_counter: Optional[str] = None,
                 degrade: Optional[Callable[[Any, BaseException], Any]] = None):
        self.retries = max(1, int(retries))
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.retry_counter = retry_counter
        self.degrade = degrade

    def run(self, fn: Callable[[Any], Any], value: Any,
            point: Optional[str] = None) -> Any:
        last: Optional[BaseException] = None
        for attempt in range(self.retries):
            try:
                if point is not None:
                    fault_point(point)
                return fn(value)
            except Exception as e:  # noqa: BLE001 — retried, then raised
                last = e
                if attempt == self.retries - 1:
                    break
                if self.retry_counter is not None:
                    core_telemetry.incr(self.retry_counter)
                _clock_sleep(min(self.backoff_s * (2 ** attempt),
                                 self.backoff_cap_s))
        if self.degrade is not None:
            return self.degrade(value, last)
        raise last  # type: ignore[misc]


class Stage:
    """One named map stage: `fn(value) -> value`, run by `workers`
    threads under a bounded credit budget.

    Registered subclasses (AdmissionStage here, io.feed.H2DStage,
    serving.batcher.PrefillStage) must declare a static class-level
    `name` and a bounded positive `credits` budget, and their
    `flow.<name>.*` metric rows must appear in DECLARED_METRICS — lint
    rule G405 enforces both.  Anonymous per-graph stages (built from a
    dynamic name, e.g. by HostPipeline) instantiate this base class
    directly and inherit the graph's default budget.

    `fn` must be thread-safe for workers > 1; `policy` wires the
    retry-then-degrade ladder around every call."""

    name: str = "stage"
    credits: Optional[int] = None  # None: the graph's default budget
    workers: int = 1
    policy: Optional[StagePolicy] = None

    def __init__(self, name: Optional[str] = None,
                 fn: Optional[Callable[[Any], Any]] = None,
                 workers: Optional[int] = None,
                 credits: Optional[int] = None,
                 policy: Optional[StagePolicy] = None):
        if name is not None:
            self.name = str(name)
        self.fn = fn
        if workers is not None:
            self.workers = max(1, int(workers))
        if credits is not None:
            self.credits = max(1, int(credits))
        if policy is not None:
            self.policy = policy

    def process(self, value: Any) -> Any:
        """The stage's work on one value; subclasses override this (or
        pass `fn`)."""
        if self.fn is None:
            return value
        return self.fn(value)

    def run_item(self, value: Any, point: str) -> Any:
        """One item through the fault point (+ policy ladder if set)."""
        if self.policy is not None:
            return self.policy.run(self.process, value, point)
        fault_point(point)
        return self.process(value)


class FlowGraph:
    """Bounded multi-stage streaming dataflow over an item iterable.

    Drive it one of three ways:
      * `run(items)` — iterate the ordered final-stage outputs
        (`yield_expired=True` to receive `Expired` markers instead of
        skipping them);
      * `start(items)` + manual `_next_out()` draining (tests, the
        HostPipeline/FeedSource adapters);
      * as the engine under `io.pipeline.HostPipeline`, which adds the
        legacy `io.pipeline.*` metric mirror.

    One graph instance is single-use (credits and counters are per run);
    instances are cheap — threads spawn at `start`, named `flow-*` and
    daemon (tests/conftest.py leak-checks the prefix)."""

    def __init__(self, stages: Sequence[Stage],
                 queue_size: Optional[int] = None,
                 deadline: Optional[float] = None,
                 span_prefix: str = "flow",
                 telemetry: Optional[Any] = None,
                 on_depth: Optional[Callable[[str, int], None]] = None,
                 on_item: Optional[Callable[[str, int, float], None]] = None,
                 on_expired: Optional[Callable[[str, FlowItem], None]] = None,
                 label: Optional[str] = None):
        if not stages:
            raise ValueError("FlowGraph needs at least one stage")
        self.stages = list(stages)
        # default budget: deep enough that every worker of the widest
        # stage can have one item in hand and one queued; small enough
        # to bound host memory
        self.queue_size = max(2, int(
            queue_size if queue_size is not None
            else 2 * max(s.workers for s in self.stages)))
        self.deadline = deadline
        self.span_prefix = span_prefix
        self.telemetry = telemetry  # optional PipelineTelemetry-style sink
        self._on_depth = on_depth
        self._on_item = on_item
        self._on_expired = on_expired
        self._label = label if label is not None else "FlowGraph"
        # one credit budget per stage (declared or the graph default),
        # plus the out queue's; hand-off queues are bounded to exactly
        # the budget, so depth can never exceed it
        self._budgets = [int(s.credits) if s.credits else self.queue_size
                         for s in self.stages] + [self.queue_size]
        self._credits = [_Credits(b) for b in self._budgets]
        self._queues: List["queue.Queue"] = []
        self._qnames = [s.name for s in self.stages] + ["out"]
        self._cancelled = threading.Event()
        self._err_lock = make_lock("flow.err")
        self._error: Optional[BaseException] = None
        # every stage worker and the producer race through _enqueue; the
        # read-modify-write max-merge below needs its own (tiny) lock
        self._hw_lock = make_lock("flow.high_water")
        self._high_water: Dict[str, int] = {}  #: guarded-by self._hw_lock
        self._started = False
        self._ctx = None  # (trace_id, span_id) captured at start
        for s in self.stages:
            _register_fault_point(f"flow.{s.name}")
        if _SAN is not None:
            _SAN.on_graph(self)

    # ---- lifecycle -----------------------------------------------------
    def start(self, items: Iterable[Any]):
        """Spawn the producer and every stage's workers (all daemon)."""
        if self._started:
            raise RuntimeError(f"{self._label} instances are single-use")
        self._started = True
        # spans from worker threads attach to the trace active where the
        # graph was STARTED (the transform/fit/serving caller), the same
        # cross-thread hop record_span exists for
        self._ctx = core_telemetry.current_context()
        self._queues = [queue.Queue(maxsize=b) for b in self._budgets]
        threading.Thread(target=self._produce, args=(items,), daemon=True,
                         name="flow-producer").start()
        for i, stage in enumerate(self.stages):
            reorder = _Reorder(lambda item, j=i: self._handoff(j, item))
            for w in range(stage.workers):
                threading.Thread(
                    target=self._worker, args=(stage, i, reorder),
                    daemon=True,
                    name=f"flow-{stage.name}-{w}").start()

    def cancel(self):
        """Stop all workers promptly; safe to call repeatedly."""
        self._cancelled.set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def high_water(self) -> Dict[str, int]:
        """Max observed depth per hand-off queue (keyed by the stage the
        queue feeds, plus 'out') — the structural overlap witness: a
        stage queue that reached depth >= 2 had the previous stage
        running ahead while this one was still busy."""
        with self._hw_lock:
            return dict(self._high_water)

    def _note_depth(self, name: str, depth: int) -> None:
        """Max-merge one depth observation; lost updates here would
        under-report overlap and silently pass the structural check."""
        with self._hw_lock:
            if depth > self._high_water.get(name, 0):
                self._high_water[name] = depth

    # ---- credit plumbing -----------------------------------------------
    def _enqueue(self, idx: int, item: Any):
        """Cancel-aware put + depth observation (no credit handling)."""
        q = self._queues[idx]
        while not self._cancelled.is_set():
            try:
                q.put(item, timeout=_POLL_S)
                break
            except queue.Full:
                continue
        if _SAN is not None and isinstance(item, _EOF):
            _SAN.on_eof(self, idx)
        name = self._qnames[idx]
        depth = q.qsize()
        self._note_depth(name, depth)
        core_telemetry.gauge(f"flow.queue.depth.{name}").set(depth)
        if self._on_depth is not None:
            self._on_depth(name, depth)

    def _put_into(self, idx: int, item: Any) -> bool:
        """Acquire one of hop idx's credits, then enqueue; False when the
        graph cancelled while waiting (the item is dropped — teardown)."""
        if not self._credits[idx].acquire(self._cancelled):
            return False
        self._enqueue(idx, item)
        return True

    def _handoff(self, idx: int, item: Any):
        """Reorder emission of stage idx to the next hop.  The
        downstream credit is acquired BEFORE this stage's releases, so
        every in-flight item is accounted to exactly one budget."""
        if isinstance(item, _EOF):
            self._enqueue(idx + 1, item)  # the marker rides credit-free
            return
        if self._put_into(idx + 1, item):
            self._credits[idx].release()

    def _fail(self, e: BaseException):
        with self._err_lock:
            if self._error is None:
                self._error = e
        self.cancel()

    # ---- threads -------------------------------------------------------
    def _produce(self, items: Iterable[Any]):
        n = 0
        try:
            for item in items:
                fi = (item if isinstance(item, FlowItem)
                      else FlowItem(item, self.deadline))
                if not self._put_into(0, (n, fi)):
                    return  # cancelled while waiting for a credit
                n += 1
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            self._fail(e)
            return
        self._enqueue(0, _EOF(n))

    def _expire(self, stage: Stage, seq: int, fi: FlowItem,
                reorder: _Reorder):
        """Shed a lapsed item at this stage boundary: count it, tell the
        graph owner, and keep its slot moving so ordering survives."""
        core_telemetry.incr("flow.expired")
        core_telemetry.incr(f"flow.expired.{stage.name}")
        if self._on_expired is not None:
            self._on_expired(stage.name, fi)
        reorder.emit(seq, Expired(fi.value, fi.deadline, stage.name))

    def _worker(self, stage: Stage, idx: int, reorder: _Reorder):
        in_q = self._queues[idx]
        point = f"flow.{stage.name}"
        while not self._cancelled.is_set():
            try:
                item = in_q.get(timeout=_POLL_S)
            except queue.Empty:
                continue
            if isinstance(item, _EOF):
                # sibling workers need the marker too
                self._enqueue(idx, item)
                reorder.close(item.total)
                return
            seq, fi = item
            if isinstance(fi, Expired):
                reorder.emit(seq, fi)  # already shed upstream: pass through
                continue
            if fi.expired():
                self._expire(stage, seq, fi, reorder)
                continue
            t0 = time.perf_counter()
            try:
                # profiler annotation only when armed via
                # enable_device_annotations() — same name as the
                # record_span below so timelines and traces line up
                with core_telemetry.device_annotation(
                        f"{self.span_prefix}.{stage.name}"):
                    out = stage.run_item(fi.value, point)
            except BaseException as e:  # noqa: BLE001 — forwarded
                self._fail(e)
                return
            dt = time.perf_counter() - t0
            if self.telemetry is not None:
                self.telemetry.add(stage.name, busy_s=dt, items=1)
            core_telemetry.histogram("flow.stage.latency",
                                     stage=stage.name).observe(dt)
            core_telemetry.incr(f"flow.items.{stage.name}")
            if self._on_item is not None:
                self._on_item(stage.name, seq, dt)
            if self._ctx is not None:
                core_telemetry.record_span(
                    f"{self.span_prefix}.{stage.name}", self._ctx, dt,
                    seq=seq)
            reorder.emit(seq, FlowItem(out, fi.deadline))

    # ---- consumption ---------------------------------------------------
    def _next_out(self, block: bool = True):
        """Next ordered (seq, FlowItem-or-Expired) from the out queue;
        `_EOF` at clean end; raises the graph's error, or queue.Empty
        when non-blocking and nothing is ready."""
        q = self._queues[-1]
        while True:
            try:
                item = q.get(block=block, timeout=_POLL_S if block else None)
            except queue.Empty:
                if self._error is not None:
                    raise self._error
                if self._cancelled.is_set():
                    raise RuntimeError(f"{self._label} cancelled")
                if block:
                    continue
                raise
            if isinstance(item, _EOF):
                if self._error is not None:
                    raise self._error
                if _SAN is not None:
                    # clean end-of-stream: every credit must be home —
                    # the sanitizer audits the ledger at this instant
                    _SAN.on_graph_eof(self)
                return item
            self._credits[-1].release()
            return item

    def run(self, items: Iterable[Any], yield_expired: bool = False):
        """Start and iterate the ordered final-stage outputs.  Expired
        items are skipped by default (the io semantics: a lapsed budget
        sheds the work, order is preserved); `yield_expired=True` yields
        the `Expired` markers in their slots instead (the serving
        semantics: map each to 504)."""
        self.start(items)
        try:
            while True:
                item = self._next_out()
                if isinstance(item, _EOF):
                    return
                payload = item[1]
                if isinstance(payload, Expired):
                    if yield_expired:
                        yield payload
                    continue
                yield payload.value
        finally:
            # an abandoned/broken consumer must not strand the workers
            self.cancel()


class AdmissionStage(Stage):
    """The serving intake as a flow stage: credit-bounded admission with
    shed, expired-deadline reaping, and graceful drain as ONE code path
    (ContinuousBatcher rides this; WorkerServer/gateway share the
    deadline helpers and counters).

    The intake is two-phase like the batcher always was: client threads
    `offer()`/`put()` into the pending queue; the single loop thread
    moves it into the loop-owned `buffer` FIFO (`drain_to_buffer`),
    reaps lapsed deadlines (`reap_expired`) and admits from the head.
    `max_pending=None` keeps the seed's unbounded never-shedding intake
    while the class still declares a bounded default budget."""

    name = "admission"
    credits = 64  # bounded default intake budget

    def __init__(self, max_pending: Optional[int] = None,
                 label: str = "admission",
                 shed_counter: Optional[str] = None,
                 expired_counter: Optional[str] = None,
                 depth_gauge: Optional[str] = None):
        super().__init__()
        self.max_pending = (None if max_pending is None
                            else int(max_pending))
        self._intake_label = label
        self._shed_counter = shed_counter
        self._expired_counter = expired_counter
        self._depth_gauge = depth_gauge
        # intake is bounded at offer(): past max_pending it sheds with
        # Overloaded/503 instead of blocking the client thread on a full
        # put
        self._pending: "queue.Queue" = queue.Queue()  # graftlint: disable=G403
        # loop-thread-only FIFO between intake and admission (the owner
        # may defer the head, e.g. paged mode waiting for pages)
        self.buffer: deque = deque()
        _register_fault_point("flow.admission")

    # ---- depth ---------------------------------------------------------
    def depth(self) -> int:
        return self._pending.qsize() + len(self.buffer)

    def _note_depth(self) -> int:
        d = self.depth()
        core_telemetry.gauge("flow.queue.depth.admission").set(d)
        if self._depth_gauge is not None:
            core_telemetry.gauge(self._depth_gauge).set(d)
        return d

    # ---- client side ---------------------------------------------------
    def shed_check(self) -> None:
        """Raise Overloaded when the bounded intake is full (the caller
        maps it to 503 + Retry-After).  Also the stage's fault point: a
        chaos plan can shed or stall admissions on demand."""
        fault_point("flow.admission")
        if self.max_pending is not None and self.depth() >= self.max_pending:
            core_telemetry.incr("flow.shed")
            core_telemetry.incr("flow.shed.admission")
            if self._shed_counter is not None:
                core_telemetry.incr(self._shed_counter)
            raise Overloaded(
                f"{self._intake_label} intake full "
                f"({self.max_pending} pending)")

    def put(self, item: Any) -> None:
        """Enqueue after a passed shed_check (the caller may validate in
        between — the batcher holds its submit lock across the gap)."""
        self._pending.put(item)
        self._note_depth()

    def offer(self, item: Any) -> None:
        """shed_check + put in one step, for callers with no validation
        between the two."""
        self.shed_check()
        self.put(item)

    # ---- loop side -----------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Any:
        """Blocking pop from the raw intake (the idle-loop path); raises
        queue.Empty on timeout."""
        return self._pending.get(timeout=timeout)

    def drain_to_buffer(self) -> None:
        """Move everything pending into the loop-owned buffer FIFO."""
        while True:
            try:
                self.buffer.append(self._pending.get_nowait())
            except queue.Empty:
                break
        self._note_depth()

    def reap_expired(self, deadline_of: Callable[[Any], Optional[float]],
                     on_expired: Callable[[Any], None],
                     now: Optional[float] = None) -> int:
        """Fail-fast pass over the buffered FIFO: an expired item must
        not consume admission work — its client has already given up.
        `on_expired` settles each dropped item (504 / TimeoutError on
        its stream); returns the number reaped."""
        now = _clock_monotonic() if now is None else now
        kept = [item for item in self.buffer
                if not deadline_expired(deadline_of(item), now)]
        reaped = [item for item in self.buffer
                  if deadline_expired(deadline_of(item), now)]
        if reaped:
            self.buffer.clear()
            self.buffer.extend(kept)
            for item in reaped:
                core_telemetry.incr("flow.expired")
                core_telemetry.incr("flow.expired.admission")
                if self._expired_counter is not None:
                    core_telemetry.incr(self._expired_counter)
                on_expired(item)
            self._note_depth()
        return len(reaped)

    def drain_all(self, on_item: Callable[[Any], None]) -> None:
        """Graceful drain: hand every queued item (buffer then pending)
        to `on_item` so stop() paths settle them consistently."""
        for item in self.buffer:
            on_item(item)
        self.buffer.clear()
        while True:
            try:
                on_item(self._pending.get_nowait())
            except queue.Empty:
                break
        self._note_depth()
