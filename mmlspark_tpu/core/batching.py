"""Iterator batching engines: the async host->device feed pattern.

Reference: core stages/Batchers.scala:12-153 — `DynamicBufferedBatcher`
(background prefetch thread + BlockingQueue), `FixedBufferedBatcher`,
`FixedBatcher`, `TimeIntervalBatcher`.  On TPU these drive double-buffered
`device_put` feeds so host batching overlaps device compute.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, List, Optional, TypeVar

from .telemetry import gauge

T = TypeVar("T")

__all__ = [
    "fixed_batcher",
    "FixedBufferedBatcher",
    "DynamicBufferedBatcher",
    "time_interval_batcher",
    "buffered_prefetch",
]


def buffered_prefetch(it: Iterable[T], buffer_size: int = 2) -> Iterator[T]:
    """Run `it` on a background thread, keeping up to `buffer_size` items
    ready — the double-buffered host->device feed (Batchers.scala:65): host
    batch assembly overlaps device compute of the previous batch.
    """
    q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
    sentinel = object()
    err: List[BaseException] = []

    def run():
        try:
            for x in it:
                q.put(x)
        except BaseException as e:  # noqa: BLE001 — forwarded to consumer
            err.append(e)
        finally:
            q.put(sentinel)

    threading.Thread(target=run, daemon=True,
                     name="stream-iter-producer").start()
    while True:
        item = q.get()
        if item is sentinel:
            if err:
                raise err[0]
            return
        yield item


def fixed_batcher(it: Iterable[T], batch_size: int) -> Iterator[List[T]]:
    """FixedBatcher (Batchers.scala:117): eager fixed-size chunks."""
    batch: List[T] = []
    for x in it:
        batch.append(x)
        if len(batch) == batch_size:
            yield batch
            batch = []
    if batch:
        yield batch


class _BufferedBatcherBase:
    _SENTINEL = object()

    def __init__(self, buffer_size: int):
        self._q: "queue.Queue" = queue.Queue(maxsize=buffer_size)
        self._err: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._consumed = False

    def _mark_consumed(self):
        if self._consumed:
            raise RuntimeError(
                f"{type(self).__name__} is single-use and already consumed"
            )
        self._consumed = True

    def _start(self, producer):
        def run():
            try:
                producer()
            except BaseException as e:  # noqa: BLE001 — forwarded to consumer
                self._err = e
            finally:
                self._q.put(self._SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="stream-batch-prefetch")
        self._thread.start()

    def __iter__(self):
        self._mark_consumed()
        while True:
            item = self._q.get()
            # depth after the take: >0 sustained means the producer is
            # running ahead (prefetch working); pinned at 0 means the
            # consumer is starved
            gauge("core.batching.queue.depth").set(self._q.qsize())
            if item is self._SENTINEL:
                if self._err is not None:
                    raise self._err
                return
            yield item


class FixedBufferedBatcher(_BufferedBatcherBase):
    """Fixed-size batches built on a background thread (double buffering).

    Reference: Batchers.scala:65 (FixedBufferedBatcher).
    """

    def __init__(self, it: Iterable[T], batch_size: int, buffer_size: int = 2):
        super().__init__(buffer_size)
        self.batch_size = batch_size

        def produce():
            for b in fixed_batcher(it, batch_size):
                self._q.put(b)

        self._start(produce)


class DynamicBufferedBatcher(_BufferedBatcherBase):
    """Drain-queue batching: the producer thread enqueues single elements;
    the consumer drains everything currently available into one batch —
    batch size adapts to the consumer/producer speed ratio.

    Reference: Batchers.scala:12 (DynamicBufferedBatcher).
    """

    def __init__(self, it: Iterable[T], max_buffer: int = 1024):
        super().__init__(max_buffer)

        def produce():
            for x in it:
                self._q.put(x)

        self._start(produce)

    def __iter__(self):
        self._mark_consumed()
        done = False
        while not done:
            batch: List[T] = []
            item = self._q.get()  # block for at least one
            if item is self._SENTINEL:
                break
            batch.append(item)
            while True:
                try:
                    item = self._q.get_nowait()
                except queue.Empty:
                    break
                if item is self._SENTINEL:
                    done = True
                    break
                batch.append(item)
            if batch:
                yield batch
        if self._err is not None:
            raise self._err


def time_interval_batcher(
    it: Iterable[T], interval_ms: float, max_batch: Optional[int] = None
) -> Iterator[List[T]]:
    """TimeIntervalBatcher (Batchers.scala:131): flush every `interval_ms`."""
    batch: List[T] = []
    deadline = time.monotonic() + interval_ms / 1e3
    for x in it:
        batch.append(x)
        now = time.monotonic()
        if now >= deadline or (max_batch and len(batch) >= max_batch):
            yield batch
            batch = []
            deadline = now + interval_ms / 1e3
    if batch:
        yield batch
