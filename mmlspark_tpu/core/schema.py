"""Columnar Table: the framework's DataFrame-equivalent.

The reference builds on Spark DataFrames; this framework is TPU-first, so the core
data structure is a host-side *columnar batch* designed to feed `jax.device_put`
directly: every column is a NumPy array (dense numeric columns are device-feedable
as-is; ragged / object columns hold Python values).  Column-level metadata mirrors
Spark's column metadata (categorical maps, label/score tagging):

  - categorical metadata  <- reference core/schema/Categoricals.scala:150
  - label/score tagging   <- reference core/schema/SparkSchema.scala:11
  - image schema          <- reference core/schema/ImageSchemaUtils.scala:9
  - findUnusedColumnName  <- reference core/schema/DatasetExtensions.scala:11
"""
from __future__ import annotations

import numpy as np
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

__all__ = [
    "Table",
    "CategoricalMap",
    "find_unused_column_name",
    "features_matrix",
    "IMAGE_FIELDS",
    "is_image_column",
]


def features_matrix(col: np.ndarray, dtype=np.float32) -> np.ndarray:
    """Densify a features column to an (N, D) matrix: typed 2-D columns pass
    through, object columns of per-row vectors are stacked."""
    if col.dtype == object:
        return np.stack([np.asarray(v, dtype=dtype) for v in col])
    return np.asarray(col, dtype=dtype)

# Spark-style image row: struct<origin,height,width,nChannels,mode,data>
# (reference org/apache/spark/ml/source/image schema; ImageSchemaUtils.scala:9).
IMAGE_FIELDS = ("origin", "height", "width", "nChannels", "mode", "data")


def _as_column(values: Any) -> np.ndarray:
    """Coerce to a 1-D (or n-D with leading row axis) numpy array.

    Lists of scalars become typed arrays; ragged lists become object arrays.
    """
    if isinstance(values, np.ndarray):
        return values
    if isinstance(values, (list, tuple)):
        try:
            arr = np.asarray(values)
            if arr.dtype == object or arr.dtype.kind in "OSU" and not all(
                isinstance(v, str) for v in values
            ):
                raise ValueError
            return arr
        except ValueError:
            arr = np.empty(len(values), dtype=object)
            for i, v in enumerate(values):
                arr[i] = v
            return arr
    raise TypeError(f"cannot build column from {type(values)}")


class CategoricalMap:
    """Bidirectional value<->index map stored as column metadata.

    Reference: core/schema/Categoricals.scala:150-314 (CategoricalMap / CategoricalUtilities).
    """

    def __init__(self, levels: Sequence[Any], ordinal: bool = False):
        self.levels: List[Any] = list(levels)
        self.ordinal = bool(ordinal)
        self._index: Dict[Any, int] = {v: i for i, v in enumerate(self.levels)}

    def get_index(self, value: Any) -> int:
        return self._index[value]

    def get_index_option(self, value: Any) -> Optional[int]:
        return self._index.get(value)

    def get_level(self, index: int) -> Any:
        return self.levels[int(index)]

    def __len__(self) -> int:
        return len(self.levels)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, CategoricalMap)
            and self.levels == other.levels
            and self.ordinal == other.ordinal
        )

    def to_json(self) -> dict:
        return {"levels": [_json_safe(v) for v in self.levels], "ordinal": self.ordinal}

    @staticmethod
    def from_json(d: dict) -> "CategoricalMap":
        return CategoricalMap(d["levels"], d.get("ordinal", False))


def _json_safe(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    return v


def find_unused_column_name(prefix: str, existing: Iterable[str]) -> str:
    """Reference: core/schema/DatasetExtensions.scala:11 (findUnusedColumnName)."""
    existing = set(existing)
    if prefix not in existing:
        return prefix
    i = 1
    while f"{prefix}_{i}" in existing:
        i += 1
    return f"{prefix}_{i}"


def is_image_column(table: "Table", col: str) -> bool:
    """True if the column holds image-struct dicts (ImageSchemaUtils.scala:9)."""
    if col not in table.columns:
        return False
    arr = table[col]
    if arr.dtype != object or len(arr) == 0:
        return False
    v = arr[0]
    return isinstance(v, dict) and {"height", "width", "nChannels", "data"} <= set(v)


class Table:
    """An ordered, immutable-by-convention columnar batch.

    Columns are numpy arrays sharing a leading row axis.  `meta` carries
    per-column metadata dicts (e.g. {"categorical": CategoricalMap, "ml_attr":...}).
    """

    def __init__(
        self,
        columns: Mapping[str, Any],
        meta: Optional[Mapping[str, dict]] = None,
    ):
        self.columns: Dict[str, np.ndarray] = {}
        n = None
        for name, values in columns.items():
            arr = _as_column(values)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column '{name}' has {len(arr)} rows, expected {n}"
                )
            self.columns[name] = arr
        self._num_rows = 0 if n is None else int(n)
        self.meta: Dict[str, dict] = {k: dict(v) for k, v in (meta or {}).items()}

    # ---- construction -------------------------------------------------
    @staticmethod
    def from_pandas(df) -> "Table":
        cols = {}
        for c in df.columns:
            s = df[c]
            if s.dtype == object:
                cols[c] = _as_column(list(s))
            else:
                cols[c] = s.to_numpy()
        return Table(cols)

    @staticmethod
    def from_records(records: Sequence[Mapping[str, Any]], names: Optional[Sequence[str]] = None) -> "Table":
        if not records:
            return Table({name: [] for name in (names or [])})
        names = list(names or records[0].keys())
        return Table({n: [r.get(n) for r in records] for n in names})

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 or v.dtype == object else v
                             for k, v in self.columns.items()})

    # ---- basic accessors ----------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    @property
    def column_names(self) -> List[str]:
        return list(self.columns.keys())

    def __getitem__(self, name: str) -> np.ndarray:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def get_meta(self, name: str) -> dict:
        return self.meta.get(name, {})

    def rows(self) -> Iterator[Dict[str, Any]]:
        names = self.column_names
        for i in range(self._num_rows):
            yield {n: self.columns[n][i] for n in names}

    # ---- transformations (all return new Tables) ----------------------
    def with_column(self, name: str, values: Any, meta: Optional[dict] = None) -> "Table":
        cols = dict(self.columns)
        arr = _as_column(values)
        if self.columns and len(arr) != self._num_rows:
            raise ValueError(
                f"column '{name}' has {len(arr)} rows, expected {self._num_rows}"
            )
        cols[name] = arr
        new_meta = dict(self.meta)
        if meta is not None:
            new_meta[name] = dict(meta)
        return Table(cols, new_meta)

    def with_meta(self, name: str, meta: dict) -> "Table":
        new_meta = dict(self.meta)
        new_meta[name] = dict(meta)
        return Table(self.columns, new_meta)

    def select(self, names: Sequence[str]) -> "Table":
        return Table({n: self.columns[n] for n in names},
                     {n: m for n, m in self.meta.items() if n in names})

    def drop(self, *names: str) -> "Table":
        drop = set(names)
        return Table({n: v for n, v in self.columns.items() if n not in drop},
                     {n: m for n, m in self.meta.items() if n not in drop})

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        cols = {mapping.get(n, n): v for n, v in self.columns.items()}
        meta = {mapping.get(n, n): m for n, m in self.meta.items()}
        return Table(cols, meta)

    def take(self, indices) -> "Table":
        idx = np.asarray(indices)
        return Table({n: v[idx] for n, v in self.columns.items()}, self.meta)

    def slice(self, start: int, stop: Optional[int] = None) -> "Table":
        sl = slice(start, stop)
        return Table({n: v[sl] for n, v in self.columns.items()}, self.meta)

    def filter(self, mask) -> "Table":
        mask = np.asarray(mask, dtype=bool)
        return Table({n: v[mask] for n, v in self.columns.items()}, self.meta)

    def head(self, n: int = 5) -> "Table":
        return self.slice(0, n)

    def map_column(self, name: str, fn: Callable[[Any], Any], out: Optional[str] = None) -> "Table":
        out = out or name
        return self.with_column(out, [fn(v) for v in self.columns[name]])

    def iter_batches(self, batch_size: int) -> Iterator["Table"]:
        for start in range(0, self._num_rows, batch_size):
            yield self.slice(start, start + batch_size)

    def shuffle(self, seed: int = 0) -> "Table":
        rng = np.random.default_rng(seed)
        return self.take(rng.permutation(self._num_rows))

    def sort_by(self, name: str, descending: bool = False) -> "Table":
        order = np.argsort(self.columns[name], kind="stable")
        if descending:
            order = order[::-1]
        return self.take(order)

    def group_indices(self, name: str) -> Dict[Any, np.ndarray]:
        """Map each distinct key in `name` to the row indices holding it."""
        out: Dict[Any, List[int]] = {}
        for i, v in enumerate(self.columns[name]):
            key = v.item() if isinstance(v, np.generic) else v
            out.setdefault(key, []).append(i)
        return {k: np.asarray(ix) for k, ix in out.items()}

    @staticmethod
    def concat(tables: Sequence["Table"]) -> "Table":
        tables = [t for t in tables if t.num_rows > 0] or list(tables[:1])
        if not tables:
            return Table({})
        names = tables[0].column_names
        cols = {}
        for n in names:
            parts = [t.columns[n] for t in tables]
            if any(p.dtype == object for p in parts):
                merged = np.empty(sum(len(p) for p in parts), dtype=object)
                i = 0
                for p in parts:
                    merged[i : i + len(p)] = p
                    i += len(p)
                cols[n] = merged
            else:
                cols[n] = np.concatenate(parts, axis=0)
        meta = {}
        for t in tables:
            meta.update(t.meta)
        return Table(cols, meta)

    # ---- equality (used by the fuzzing harness) ------------------------
    def approx_equals(self, other: "Table", rtol: float = 1e-5, atol: float = 1e-6) -> bool:
        """DataFrameEquality analog (reference core/test/base/TestBase.scala)."""
        if self.column_names != other.column_names or self.num_rows != other.num_rows:
            return False
        for n in self.column_names:
            a, b = self.columns[n], other.columns[n]
            if a.dtype == object or b.dtype == object:
                for x, y in zip(a, b):
                    if not _values_close(x, y, rtol, atol):
                        return False
            elif a.dtype.kind in "fc":
                if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
                    return False
            else:
                if not np.array_equal(a, b):
                    return False
        return True

    def __repr__(self) -> str:
        spec = ", ".join(
            f"{n}:{v.dtype}{list(v.shape[1:]) if v.ndim > 1 else ''}"
            for n, v in self.columns.items()
        )
        return f"Table[{self._num_rows} rows]({spec})"


def _values_close(x, y, rtol, atol) -> bool:
    if x is None or y is None:
        return x is None and y is None
    if isinstance(x, dict) and isinstance(y, dict):
        return set(x) == set(y) and all(_values_close(x[k], y[k], rtol, atol) for k in x)
    if isinstance(x, (list, tuple, np.ndarray)) or isinstance(y, (list, tuple, np.ndarray)):
        xa, ya = np.asarray(x), np.asarray(y)
        if xa.shape != ya.shape:
            return False
        if xa.dtype == object:
            return all(_values_close(a, b, rtol, atol) for a, b in zip(xa.ravel(), ya.ravel()))
        if xa.dtype.kind in "fc":
            return bool(np.allclose(xa, ya, rtol=rtol, atol=atol, equal_nan=True))
        return bool(np.array_equal(xa, ya))
    if isinstance(x, float) or isinstance(y, float):
        return bool(np.isclose(float(x), float(y), rtol=rtol, atol=atol, equal_nan=True))
    return x == y
