"""Stage registry: the reflection backbone.

Reference: core/utils/JarLoadingUtils.scala:43 walks the classpath to find all
`Wrappable` stages; codegen and the fuzzing harness (FuzzingTest.scala) build on
it.  Here stages self-register via decorator; `all_stages()` drives the
auto-fuzzing test harness and the bindings generator.
"""
from __future__ import annotations

import importlib
from typing import Dict, List, Optional, Type

_REGISTRY: Dict[str, Type] = {}

# modules whose import registers all public stages (kept in sync as the
# framework grows; mirrored by mmlspark_tpu/__init__ lazy imports)
STAGE_MODULES = [
    "mmlspark_tpu.core.pipeline",
    "mmlspark_tpu.stages",
    "mmlspark_tpu.ops.image_stages",
    "mmlspark_tpu.models.tpu_model",
    "mmlspark_tpu.models.image_featurizer",
    "mmlspark_tpu.models.deep_vision",
    "mmlspark_tpu.models.bilstm",
    "mmlspark_tpu.featurize.featurize",
    "mmlspark_tpu.featurize.value_indexer",
    "mmlspark_tpu.featurize.clean_missing",
    "mmlspark_tpu.featurize.text",
    "mmlspark_tpu.models.linear",
    "mmlspark_tpu.models.train_classifier",
    "mmlspark_tpu.models.statistics",
    "mmlspark_tpu.gbdt.estimators",
    "mmlspark_tpu.online.learners",
    "mmlspark_tpu.online.featurizer",
    "mmlspark_tpu.online.contextual_bandit",
    "mmlspark_tpu.automl.tune",
    "mmlspark_tpu.automl.find_best",
    "mmlspark_tpu.explainers",
    "mmlspark_tpu.nn.knn",
    "mmlspark_tpu.recommendation",
    "mmlspark_tpu.isolationforest",
    "mmlspark_tpu.io.http.transformers",
    "mmlspark_tpu.cognitive",
    "mmlspark_tpu.cyber",
]


def register_stage(cls=None, *, name: Optional[str] = None):
    def wrap(c):
        _REGISTRY[name or c.__name__] = c
        return c

    return wrap(cls) if cls is not None else wrap


def get_stage_class(name: str) -> Type:
    if name not in _REGISTRY:
        load_all_modules()
    return _REGISTRY[name]


def load_all_modules() -> List[str]:
    loaded = []
    for mod in STAGE_MODULES:
        try:
            importlib.import_module(mod)
            loaded.append(mod)
        except ModuleNotFoundError as e:
            # only suppress "this stage module isn't built yet"; a missing
            # transitive dependency inside a present module must surface,
            # or the registry silently shrinks
            if e.name != mod and not mod.startswith(f"{e.name}."):
                raise
    return loaded


def all_stages() -> Dict[str, Type]:
    load_all_modules()
    return dict(_REGISTRY)
