"""Estimator / Transformer / Pipeline: the stage graph.

SparkML semantics (fit/transform over tables, schema validation, persistence)
without Spark — reference: the Estimator/Transformer contract used throughout
mmlspark (e.g. deep-learning/.../CNTKModel.scala:500 transform,
lightgbm/LightGBMBase.scala:43 train), plus `NamespaceInjections.pipelineModel`
(core L1) and FluentAPI `df.mlTransform(stage)` (core/spark/FluentAPI.scala:12-24).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from .params import ComplexParam, Params
from .schema import Table
from .telemetry import log_verb

__all__ = [
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "LambdaTransformer",
    "ml_transform",
]


class PipelineStage(Params):
    """Common base: params + persistence + schema transform."""

    def transform_schema(self, columns: List[str]) -> List[str]:
        """Best-effort static schema check: given input column names, return
        output column names.  Subclasses override to validate inputs early
        (reference: transformSchema in every Spark stage)."""
        return columns

    # persistence — implemented via serialize.py to avoid import cycles
    def save(self, path: str, overwrite: bool = True) -> None:
        from . import serialize

        serialize.save_stage(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        from . import serialize

        return serialize.load_stage(path)


class Transformer(PipelineStage):
    def transform(self, table: Table) -> Table:
        with log_verb(self, "transform"):
            return self._transform(table)

    def _transform(self, table: Table) -> Table:
        raise NotImplementedError

    def __call__(self, table: Table) -> Table:
        return self.transform(table)


class Estimator(PipelineStage):
    def fit(self, table: Table) -> "Transformer":
        with log_verb(self, "fit"):
            return self._fit(table)

    def _fit(self, table: Table) -> "Transformer":
        raise NotImplementedError

    def fit_transform(self, table: Table) -> Table:
        return self.fit(table).transform(table)


class Model(Transformer):
    """A Transformer produced by an Estimator."""


class Pipeline(Estimator):
    """Chain of stages; fitting fits estimators in sequence on the running
    transform of the input (SparkML Pipeline semantics)."""

    stages = ComplexParam("list of PipelineStage", default=None)

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _fit(self, table: Table) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = table
        stages = self.stages or []
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
            elif isinstance(stage, Transformer):
                model = stage
                fitted.append(stage)
            else:
                raise TypeError(f"stage {i} is neither Estimator nor Transformer: {stage}")
            if i < len(stages) - 1:
                cur = model.transform(cur)
        return PipelineModel(stages=fitted)

    def transform_schema(self, columns: List[str]) -> List[str]:
        for stage in self.stages or []:
            columns = stage.transform_schema(columns)
        return columns


class PipelineModel(Model):
    stages = ComplexParam("list of fitted Transformers", default=None)

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _transform(self, table: Table) -> Table:
        for stage in self.stages or []:
            table = stage.transform(table)
        return table

    def transform_schema(self, columns: List[str]) -> List[str]:
        for stage in self.stages or []:
            columns = stage.transform_schema(columns)
        return columns


class LambdaTransformer(Transformer):
    """Arbitrary table->table function as a stage.

    Reference: core stages/Lambda.scala:22.  The function is a complex param
    (pickled on save, like the reference's UDFParam).
    """

    fn = ComplexParam("Table -> Table callable")

    def __init__(self, fn: Optional[Callable[[Table], Table]] = None, **kw):
        super().__init__(**kw)
        if fn is not None:
            self.set(fn=fn)

    def _transform(self, table: Table) -> Table:
        return self.fn(table)


def ml_transform(table: Table, *stages: Transformer) -> Table:
    """FluentAPI analog: `ml_transform(t, s1, s2)` (FluentAPI.scala:12-24)."""
    for s in stages:
        table = s.transform(table)
    return table
