"""Param system: typed, documented, serializable stage configuration.

Mirrors SparkML `Params` + MMLSpark's ComplexParam extension
(reference core/serialize/ComplexParam.scala:13; org/apache/spark/ml/param/*.scala),
re-designed for a Python-first framework: Params are class-level descriptors,
values live in an instance map, save/load splits JSON-simple values from
"complex" values (numpy arrays, nested stages, callables) which get their own
files — the same split Spark's `ComplexParamsSerializer` makes
(org/apache/spark/ml/ComplexParamsSerializer.scala).
"""
from __future__ import annotations

import copy
import uuid
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

__all__ = ["Param", "ComplexParam", "ServiceParam", "Params", "TypeConverters"]

T = TypeVar("T")


class TypeConverters:
    """Lenient converters mirroring pyspark.ml.param.TypeConverters."""

    @staticmethod
    def to_int(v):
        return int(v)

    @staticmethod
    def to_float(v):
        return float(v)

    @staticmethod
    def to_str(v):
        if not isinstance(v, str):
            raise TypeError(f"expected str, got {type(v)}")
        return v

    @staticmethod
    def to_bool(v):
        return bool(v)

    @staticmethod
    def to_list_int(v):
        return [int(x) for x in v]

    @staticmethod
    def to_list_float(v):
        return [float(x) for x in v]

    @staticmethod
    def to_list_str(v):
        return [TypeConverters.to_str(x) for x in v]

    @staticmethod
    def identity(v):
        return v


class Param(Generic[T]):
    """A named, documented parameter declared at class level.

    Works as a descriptor: `stage.my_param` reads the effective value
    (set -> default -> error); `stage.set(my_param=v)` writes.
    """

    is_complex = False
    _REQUIRED = object()  # sentinel: no default declared

    def __init__(
        self,
        doc: str = "",
        default: Any = _REQUIRED,
        converter: Optional[Callable[[Any], T]] = None,
        transient: bool = False,
    ):
        self.doc = doc
        self.has_default = default is not Param._REQUIRED
        self.default = None if not self.has_default else default
        self.converter = converter or TypeConverters.identity
        #: transient params are runtime-only hooks (delegates, live clients):
        #: skipped on save/load and excluded from round-trip equality
        self.transient = transient
        self.name: str = ""  # filled by __set_name__

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get_or_default(self.name)

    def __set__(self, obj, value):
        obj.set(**{self.name: value})

    def convert(self, value):
        return self.converter(value)

    def __repr__(self):
        return f"Param({self.name!r})"


class ComplexParam(Param):
    """Param whose value cannot round-trip through JSON (models, arrays,
    nested stages, UDFs).  Serialized to dedicated files under
    `<stage_dir>/complexParams/<name>/` — reference core/serialize/ComplexParam.scala:13.
    """

    is_complex = True


class ServiceParam(Param):
    """Value-or-column duality: the param is either a constant or the name of
    a column supplying per-row values — reference
    cognitive/CognitiveServiceBase.scala:29-126 (ServiceParam).

    Set with `stage.set(p=value)` or `stage.set_col(p, "colname")`; read with
    `stage.resolve(row_or_table)`.
    """

    def convert(self, value):
        if isinstance(value, dict) and set(value) <= {"value", "col"}:
            return value
        return {"value": self.converter(value)}


class Params:
    """Base for everything with params.  Subclasses declare `Param` class
    attributes; instances carry `_param_map` (explicitly set) and read
    defaults from the declarations.
    """

    def __init__(self, **kwargs):
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._param_map: Dict[str, Any] = {}
        if kwargs:
            self.set(**kwargs)

    # ---- declaration access -------------------------------------------
    @classmethod
    def params(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        return out

    @classmethod
    def param(cls, name: str) -> Param:
        p = cls.params().get(name)
        if p is None:
            raise KeyError(f"{cls.__name__} has no param '{name}'")
        return p

    # ---- get/set -------------------------------------------------------
    def set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self.param(name)
            if value is None:
                # pyspark semantics: setting None clears the explicit value,
                # falling back to the declared default
                self._param_map.pop(name, None)
            else:
                self._param_map[name] = p.convert(value)
        return self

    def set_col(self, name: str, col: str) -> "Params":
        p = self.param(name)
        if not isinstance(p, ServiceParam):
            raise TypeError(f"{name} is not a ServiceParam")
        self._param_map[name] = {"col": col}
        return self

    def is_set(self, name: str) -> bool:
        return name in self._param_map

    def is_defined(self, name: str) -> bool:
        return name in self._param_map or self.param(name).has_default

    def get(self, name: str) -> Any:
        return self._param_map.get(name)

    def get_or_default(self, name: str) -> Any:
        if name in self._param_map:
            return self._param_map[name]
        p = self.param(name)
        if p.has_default:
            return copy.copy(p.default) if isinstance(p.default, (list, dict)) else p.default
        raise KeyError(f"param '{name}' of {type(self).__name__} is not set and has no default")

    def resolve(self, name: str, table=None, row_index: int = None):
        """Resolve a ServiceParam to a constant or a per-row value."""
        v = self.get_or_default(name)
        if isinstance(v, dict) and "col" in v:
            if table is None:
                raise ValueError(f"param '{name}' is column-bound; need a table")
            col = table[v["col"]]
            return col if row_index is None else col[row_index]
        if isinstance(v, dict) and "value" in v:
            return v["value"]
        return v

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            cur = self._param_map.get(name, p.default if p.has_default else "<unset>")
            lines.append(f"{name}: {p.doc} (current: {cur!r})")
        return "\n".join(lines)

    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        new = copy.copy(self)
        new._param_map = dict(self._param_map)
        new.uid = self.uid
        if extra:
            new.set(**extra)
        return new

    # ---- serialization hooks (implemented in serialize.py) -------------
    def simple_param_values(self) -> Dict[str, Any]:
        return {
            n: v
            for n, v in self._param_map.items()
            if not self.param(n).is_complex
        }

    def complex_param_values(self) -> Dict[str, Any]:
        return {
            n: v for n, v in self._param_map.items()
            if self.param(n).is_complex and not self.param(n).transient
        }
