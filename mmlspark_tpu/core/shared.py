"""Process-wide lazily-constructed singletons.

Reference: core io/http/SharedVariable.scala:18 (SharedVariable) and :37
(SharedSingleton) — one instance per executor JVM, keyed by constructor.
Here: one instance per Python process (per-host in a multi-host jax job),
used for HTTP clients, loaded models, and rate-limited resources.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Generic, Hashable, Optional, TypeVar

T = TypeVar("T")

_LOCK = threading.Lock()
_SINGLETONS: Dict[Hashable, Any] = {}


class SharedVariable(Generic[T]):
    """Lazily constructed, process-shared value."""

    def __init__(self, ctor: Callable[[], T], key: Optional[Hashable] = None):
        self._ctor = ctor
        self._key = key if key is not None else id(ctor)

    def get(self) -> T:
        with _LOCK:
            if self._key not in _SINGLETONS:
                _SINGLETONS[self._key] = self._ctor()
            return _SINGLETONS[self._key]

    @property
    def value(self) -> T:
        return self.get()


def shared_singleton(key: Hashable, ctor: Callable[[], T]) -> T:
    """Get-or-create a process-wide singleton by explicit key."""
    with _LOCK:
        if key not in _SINGLETONS:
            _SINGLETONS[key] = ctor()
        return _SINGLETONS[key]


def reset_singletons() -> None:
    with _LOCK:
        _SINGLETONS.clear()
