"""Stage persistence: save/load every stage with simple + complex params.

Reference: org/apache/spark/ml/Serializer.scala:21-147 and
ComplexParamsSerializer.scala — metadata JSON for JSON-able params, a
dedicated directory per complex param (models, arrays, nested stages, UDFs).
Layout:

    <path>/metadata.json              {class, uid, params{...}}
    <path>/complexParams/<name>/      per-kind payload (npz / nested stage / pickle)
"""
from __future__ import annotations

import importlib
import json
import os
import pickle
import shutil
from typing import Any

import numpy as np

from .params import Params
from .schema import CategoricalMap, Table

_FORMAT_VERSION = 1


def _class_path(obj) -> str:
    t = type(obj)
    return f"{t.__module__}.{t.__qualname__}"


def _resolve_class(path: str):
    module, _, name = path.rpartition(".")
    mod = importlib.import_module(module)
    obj = mod
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _json_default(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (np.bool_,)):
        return bool(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    raise TypeError(f"not JSON-serializable: {type(v)}")


# ---- complex value writers/readers -------------------------------------

def _write_complex(value: Any, path: str) -> dict:
    """Write one complex value under `path`, return a descriptor dict."""
    os.makedirs(path, exist_ok=True)
    from .pipeline import PipelineStage

    if isinstance(value, PipelineStage):
        save_stage(value, os.path.join(path, "stage"))
        return {"kind": "stage"}
    if isinstance(value, (list, tuple)) and value and all(
        isinstance(v, PipelineStage) for v in value
    ):
        for i, v in enumerate(value):
            save_stage(v, os.path.join(path, f"stage_{i}"))
        return {"kind": "stage_list", "n": len(value)}
    if isinstance(value, np.ndarray):
        np.save(os.path.join(path, "array.npy"), value, allow_pickle=value.dtype == object)
        return {"kind": "ndarray"}
    if isinstance(value, dict) and value and all(
        isinstance(v, np.ndarray) for v in value.values()
    ):
        np.savez(os.path.join(path, "arrays.npz"), **value)
        return {"kind": "ndarray_dict"}
    if isinstance(value, Table):
        cols = {n: value.columns[n] for n in value.column_names}
        with open(os.path.join(path, "table.pkl"), "wb") as f:
            pickle.dump({"columns": cols, "meta": value.meta}, f)
        return {"kind": "table"}
    if isinstance(value, CategoricalMap):
        with open(os.path.join(path, "catmap.json"), "w") as f:
            json.dump(value.to_json(), f)
        return {"kind": "categorical_map"}
    # catch-all: pickle (UDFs, jax pytrees of np arrays, custom objects)
    with open(os.path.join(path, "value.pkl"), "wb") as f:
        pickle.dump(value, f)
    return {"kind": "pickle"}


def _read_complex(desc: dict, path: str) -> Any:
    kind = desc["kind"]
    if kind == "stage":
        return load_stage(os.path.join(path, "stage"))
    if kind == "stage_list":
        return [load_stage(os.path.join(path, f"stage_{i}")) for i in range(desc["n"])]
    if kind == "ndarray":
        return np.load(os.path.join(path, "array.npy"), allow_pickle=True)
    if kind == "ndarray_dict":
        with np.load(os.path.join(path, "arrays.npz")) as z:
            return {k: z[k] for k in z.files}
    if kind == "table":
        with open(os.path.join(path, "table.pkl"), "rb") as f:
            d = pickle.load(f)
        return Table(d["columns"], d["meta"])
    if kind == "categorical_map":
        with open(os.path.join(path, "catmap.json")) as f:
            return CategoricalMap.from_json(json.load(f))
    if kind == "pickle":
        with open(os.path.join(path, "value.pkl"), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown complex param kind {kind!r}")


# ---- public API --------------------------------------------------------

def save_stage(stage: Params, path: str, overwrite: bool = True) -> None:
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(path)
        shutil.rmtree(path)
    os.makedirs(path)
    complex_descs = {}
    for name, value in stage.complex_param_values().items():
        if value is None:
            complex_descs[name] = {"kind": "none"}
            continue
        complex_descs[name] = _write_complex(
            value, os.path.join(path, "complexParams", name)
        )
    meta = {
        "formatVersion": _FORMAT_VERSION,
        "class": _class_path(stage),
        "uid": stage.uid,
        "params": stage.simple_param_values(),
        "complexParams": complex_descs,
    }
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1, default=_json_default)
    # allow stages to persist extra payloads (e.g. orbax checkpoints)
    extra = getattr(stage, "_save_extra", None)
    if extra is not None:
        extra(os.path.join(path, "extra"))


def load_stage(path: str) -> Params:
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    cls = _resolve_class(meta["class"])
    stage = cls.__new__(cls)
    Params.__init__(stage)
    stage.uid = meta["uid"]
    declared = cls.params()
    for name, value in meta["params"].items():
        if name in declared:
            stage._param_map[name] = value
    for name, desc in meta.get("complexParams", {}).items():
        if desc["kind"] == "none":
            stage._param_map[name] = None
        else:
            stage._param_map[name] = _read_complex(
                desc, os.path.join(path, "complexParams", name)
            )
    extra = getattr(stage, "_load_extra", None)
    extra_path = os.path.join(path, "extra")
    if extra is not None and os.path.exists(extra_path):
        extra(extra_path)
    return stage
