"""Goodput ledger: per-step timelines and lost-time attribution.

Answers the question the elastic 3D trainer raises and the counter
plane cannot: of the wall-clock spent inside `fit_epochs_resumable`,
what fraction was *productive* step compute, and where did the rest go?

    goodput = productive_step_time / wall_time

Every step the training loop records a `StepTimeline` entry — compute
seconds plus any attributed segment seconds (h2d from feed telemetry,
checkpoint writes, guard rollbacks, ...) — and the rest of the stack
feeds one-off losses through `note_lost()`: `run_with_deadline`
attributes collective overruns, the compile sentry attributes steady-
state recompiles, the elastic shrink path attributes the host-loss
ladder (detection -> restore -> resume).  `summary()` folds the ledger
into a goodput fraction, a lost-time table keyed by `LOST_KINDS`, and a
*windowed* goodput over the last few steps — the windowed form is what
"has this host recovered" means after an elastic shrink, since a
whole-run fraction can never climb back after a multi-second loss.

The ledger arms itself on the first recorded step; `note_lost()` before
that is dropped on purpose so warm-up compiles and the initial
rendezvous (which precede training) don't read as lost *training* time.

Straggler detection (`detect_straggler`) is a pure function over
per-host step timelines — the fleet merge plane runs it on the
federated view and surfaces the slowest host as a `training.straggler`
counter + `training.straggler.ratio` gauge.  Timestamps use the
injectable `utils.faults` clock, so chaos soaks under `VirtualClock`
attribute virtual seconds consistently.
"""
from __future__ import annotations

import os
import statistics
from contextlib import contextmanager
from typing import (Callable, Dict, Iterator, List, Mapping, Optional,
                    Sequence)

from ...utils.faults import monotonic as _monotonic
from ...utils.sync import make_lock
from .metrics import REGISTRY, MetricsRegistry

__all__ = ["LOST_KINDS", "StepTimeline", "GoodputLedger", "LEDGER",
           "detect_straggler"]

#: The lost-time taxonomy (docs/observability.md "The goodput plane").
#: Everything measurable lands in one of these; wall time nobody
#: claimed shows up as `unattributed` in the summary, never silently.
LOST_KINDS = (
    "h2d",          # host->device transfer + shard put (feed telemetry)
    "collective",   # collective overrun budget (run_with_deadline)
    "checkpoint",   # autosave write + verify
    "rollback",     # guard rollback: restore + verify + rebuild
    "recompile",    # steady-state recompilation (compile sentry)
    "rendezvous",   # elastic re-rendezvous / membership epochs
    "host_loss",    # elastic shrink ladder: detection -> restore -> resume
    "quarantine",   # steps skipped while a batch is quarantined
    "other",        # explicitly attributed, fits no bucket above
)


class StepTimeline:
    """Fixed-capacity ring of per-step records for one host.

    Each record: ``{"step": int, "t_start": float, "wall_s": float,
    "segments": {"compute": s, <lost kind>: s, ...}}``.  Not
    self-locking — the owning ledger's lock guards access."""

    __slots__ = ("capacity", "_recs", "_head", "_size")

    def __init__(self, capacity: int = 256):
        self.capacity = capacity
        self._recs: List[Optional[Dict[str, object]]] = [None] * capacity
        self._head = 0
        self._size = 0

    def add(self, rec: Dict[str, object]) -> None:
        self._recs[self._head] = rec
        self._head = (self._head + 1) % self.capacity
        self._size = min(self._size + 1, self.capacity)

    def records(self) -> List[Dict[str, object]]:
        start = (self._head - self._size) % self.capacity
        return [self._recs[(start + i) % self.capacity]  # type: ignore
                for i in range(self._size)]

    def last(self, n: int) -> List[Dict[str, object]]:
        recs = self.records()
        return recs[-n:] if n > 0 else []


class GoodputLedger:
    """Per-host goodput accounting: productive vs lost wall-clock.

    `record_step()` is the per-step hot path (a few dict updates and
    two gauge writes under one lock — far under the 1% step-time
    budget); everything else is read-side."""

    def __init__(self, host_id: Optional[str] = None, capacity: int = 256,
                 window_steps: int = 8,
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self._lock = make_lock("telemetry.goodput")
        self._clock = clock if clock is not None else _monotonic
        self._registry = registry if registry is not None else REGISTRY
        self.window_steps = window_steps
        #: guarded-by self._lock (all mutable state below)
        self._host_id = host_id or f"pid{os.getpid()}"
        self._t0: Optional[float] = None
        self._productive_s = 0.0
        self._lost: Dict[str, float] = {}
        self._steps = 0
        self._timeline = StepTimeline(capacity)

    # ---- identity / lifecycle ------------------------------------------
    @property
    def host_id(self) -> str:
        with self._lock:
            return self._host_id

    def set_host(self, host_id: str) -> None:
        with self._lock:
            self._host_id = host_id

    def start(self, t: Optional[float] = None) -> None:
        """Arm the ledger (idempotent).  Normally implicit on the first
        recorded step; explicit for tests and for attributing losses
        that precede step 0 on purpose."""
        t = self._clock() if t is None else float(t)
        with self._lock:
            if self._t0 is None:
                self._t0 = t

    def reset(self, host_id: Optional[str] = None) -> None:
        with self._lock:
            if host_id is not None:
                self._host_id = host_id
            self._t0 = None
            self._productive_s = 0.0
            self._lost = {}
            self._steps = 0
            self._timeline = StepTimeline(self._timeline.capacity)

    # ---- write side ----------------------------------------------------
    def record_step(self, step: int, compute_s: float,
                    t_start: Optional[float] = None,
                    **segments: float) -> None:
        """One finished step: `compute_s` of productive time plus any
        attributed lost segments (kwargs keyed by `LOST_KINDS`)."""
        for kind in segments:
            if kind not in LOST_KINDS:
                raise ValueError(
                    f"unknown lost-time kind {kind!r} (LOST_KINDS)")
        compute_s = max(0.0, float(compute_s))
        wall = compute_s + sum(max(0.0, float(v))
                               for v in segments.values())
        t_end = self._clock()
        if t_start is None:
            t_start = t_end - wall
        seg: Dict[str, float] = {"compute": compute_s}
        with self._lock:
            if self._t0 is None:
                self._t0 = t_start
            self._productive_s += compute_s
            for kind, v in segments.items():
                v = max(0.0, float(v))
                if v > 0.0:
                    self._lost[kind] = self._lost.get(kind, 0.0) + v
                    seg[kind] = v
            self._steps += 1
            self._timeline.add({"step": int(step),
                                "t_start": round(float(t_start), 6),
                                "wall_s": round(wall, 6),
                                "segments": seg})
            frac = self._frac_locked(t_end)
            wfrac = self._window_frac_locked()
        if frac is not None:
            self._registry.gauge("training.goodput.frac").set(frac)
        if wfrac is not None:
            self._registry.gauge("training.goodput.window_frac").set(wfrac)

    def note_lost(self, kind: str, seconds: float) -> None:
        """Attribute lost wall-clock outside any step record.  Dropped
        when the ledger hasn't started (pre-training warm-up)."""
        if kind not in LOST_KINDS:
            raise ValueError(f"unknown lost-time kind {kind!r} (LOST_KINDS)")
        seconds = float(seconds)
        if seconds <= 0.0:
            return
        with self._lock:
            if self._t0 is None:
                return
            self._lost[kind] = self._lost.get(kind, 0.0) + seconds
            total = sum(self._lost.values())
        self._registry.gauge("training.goodput.lost_s").set(total)
        self._registry.gauge(f"training.goodput.lost_s.{kind}").set(
            self._lost_value(kind))

    def _lost_value(self, kind: str) -> float:
        with self._lock:
            return self._lost.get(kind, 0.0)

    @contextmanager
    def attribute(self, kind: str) -> Iterator[None]:
        """Time a block and attribute its wall to `kind`."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.note_lost(kind, self._clock() - t0)

    # ---- read side -----------------------------------------------------
    def _frac_locked(self, now: float) -> Optional[float]:
        if self._t0 is None:
            return None
        wall = now - self._t0
        if wall <= 0:
            return None
        return min(1.0, self._productive_s / wall)

    def _window_frac_locked(self) -> Optional[float]:
        recs = self._timeline.last(self.window_steps)
        if len(recs) < 2:
            return None
        first, last = recs[0], recs[-1]
        span = (float(last["t_start"]) + float(last["wall_s"])
                - float(first["t_start"]))
        if span <= 0:
            return None
        productive = sum(float(r["segments"].get("compute", 0.0))  # type: ignore
                         for r in recs)
        return min(1.0, productive / span)

    def summary(self, now: Optional[float] = None) -> Dict[str, object]:
        now = self._clock() if now is None else float(now)
        with self._lock:
            lost = dict(self._lost)
            wall = (now - self._t0) if self._t0 is not None else 0.0
            accounted = self._productive_s + sum(lost.values())
            return {
                "host_id": self._host_id,
                "steps": self._steps,
                "wall_s": round(max(0.0, wall), 6),
                "productive_s": round(self._productive_s, 6),
                "lost": {k: round(v, 6) for k, v in sorted(lost.items())},
                "unattributed_s": round(max(0.0, wall - accounted), 6),
                "goodput_frac": self._frac_locked(now),
                "window": {
                    "steps": min(len(self._timeline.records()),
                                 self.window_steps),
                    "goodput_frac": self._window_frac_locked(),
                },
            }

    def export(self) -> Dict[str, object]:
        """The wire block served under `/metrics.json` `"goodput"`."""
        with self._lock:
            steps = self._timeline.records()
        out = self.summary()
        return {"host_id": out["host_id"], "summary": out, "steps": steps}


#: Process-wide ledger the training loop and attribution hooks feed.
LEDGER = GoodputLedger()


# ---------------------------------------------------------------------------
# Straggler detection over merged per-host timelines
def detect_straggler(timelines: Mapping[str, Sequence[Mapping[str, object]]],
                     ratio: float = 2.0,
                     streak: int = 3) -> Optional[Dict[str, object]]:
    """Name the slowest host from per-host step timelines, or None.

    For every step index present on ALL hosts, compute
    `p_max / p_median` of the step wall times.  A host is a straggler
    only when it is the argmax AND over threshold for `streak`
    consecutive common steps — a single jittery step never names
    anybody.  Needs >= 3 hosts to be meaningful: with two, the median
    is the mean of the pair, so `ratio >= 2` can never fire (by design
    — two hosts can't tell you *which* one is slow).

    `timelines`: host -> step records (each with "step" and "wall_s"),
    i.e. the `steps` lists from merged goodput exports.
    """
    by_step: Dict[int, Dict[str, float]] = {}
    for host, recs in timelines.items():
        for r in recs:
            by_step.setdefault(int(r["step"]), {})[host] = float(r["wall_s"])  # type: ignore
    hosts = set(timelines)
    run_host: Optional[str] = None
    run_len = 0
    found: Optional[Dict[str, object]] = None
    for g in sorted(by_step):
        by = by_step[g]
        if set(by) != hosts or len(by) < 2:
            # a step some host never reported breaks any streak: skew
            # against a missing host is not evidence
            run_host, run_len = None, 0
            continue
        med = statistics.median(by.values())
        slow = max(by, key=lambda h: by[h])
        r = (by[slow] / med) if med > 0 else 0.0
        if r >= ratio:
            run_len = run_len + 1 if slow == run_host else 1
            run_host = slow
            if run_len >= streak:
                found = {"host": slow, "ratio": round(r, 3),
                         "streak": run_len, "step": g}
        else:
            run_host, run_len = None, 0
    return found
