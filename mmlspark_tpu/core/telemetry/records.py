"""Usage telemetry: every stage verb logs a structured JSON record.

Reference: core logging/BasicLogging.scala:25-71 — logClass/logFit/logTransform
emit `{uid, className, method, buildVersion}`.  Here: a process-local ring
buffer + stdlib logging, cheap enough to stay always-on, with wall-time
capture (also covering stages/Timer.scala:55 TimerModel semantics).

The ring is a `deque(maxlen=4096)` guarded by a lock: CPython deque
append/iteration is GIL-atomic for plain appends, but `recent_records()`
snapshots and `clear_records()` must not interleave with a concurrent
append mid-iteration (RuntimeError: deque mutated during iteration), so
all three paths take `_RECORDS_LOCK`.  The maxlen bound is what keeps
always-on verb logging (and span-heavy serving runs that also log verbs)
from growing host memory — pinned by tests/test_observability.py.
"""
from __future__ import annotations

import collections
import contextlib
import json
import logging
import threading
import time
from typing import Any, Deque, Dict

from ... import version

__all__ = ["log_verb", "recent_records", "clear_records", "RECORDS_MAXLEN",
           "logger"]

logger = logging.getLogger("mmlspark_tpu.telemetry")

RECORDS_MAXLEN = 4096

_RECORDS: Deque[Dict[str, Any]] = collections.deque(maxlen=RECORDS_MAXLEN)
_RECORDS_LOCK = threading.Lock()


def recent_records():
    with _RECORDS_LOCK:
        return list(_RECORDS)


def clear_records():
    with _RECORDS_LOCK:
        _RECORDS.clear()


@contextlib.contextmanager
def log_verb(stage, method: str, **extra):
    """Extra keyword fields are merged into the record verbatim (callers
    pass JSON-safe values — e.g. the compile sentry naming a triggering
    shape); they never override the core fields."""
    t0 = time.perf_counter()
    err = None
    try:
        yield
    except Exception as e:  # noqa: BLE001 — record then re-raise
        err = type(e).__name__
        raise
    finally:
        rec = dict(extra)
        rec.update({
            "uid": getattr(stage, "uid", "?"),
            "className": type(stage).__name__,
            "method": method,
            "buildVersion": version.__version__,
            "wallTimeSec": round(time.perf_counter() - t0, 6),
        })
        if err:
            rec["error"] = err
        with _RECORDS_LOCK:
            _RECORDS.append(rec)
        logger.debug("%s", json.dumps(rec))
