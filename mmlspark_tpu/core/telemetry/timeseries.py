"""In-process time-series engine: bounded recent history for metrics.

The registry (metrics.py) answers "what is the value *now*"; this module
answers "what happened *lately*" without any external TSDB — the
Monarch-style pattern of keeping a fixed-capacity ring of recent samples
in-process and querying it cheaply.  A `TimeSeriesStore` samples the
declared series in `SAMPLED_SERIES` on a configurable cadence (one
`tick()` per training step is the intended driver; the cadence gate
makes extra ticks free) and supports PromQL-shaped queries over any
window: `rate()`, `delta()` (both counter-reset aware),
`quantile_over_time()`, and cadence-aligned window extraction.

Design points, mirroring the rest of the telemetry plane:

- **Declared series table.**  `SAMPLED_SERIES` is a plain dict literal
  (name -> "counter" | "gauge" | "histogram"), AST-parseable the same
  way graftlint parses `DECLARED_METRICS`; the M004 lint checks every
  key here resolves to a declared metric so the sampler never chases a
  renamed series.  Histogram-kind entries are sampled as two derived
  counter series, `<name>.count` and `<name>.sum` (cumulative, so rate
  over them gives throughput and mean latency over any window).
- **Lock striping.**  Series rings are striped across `_N_STRIPES`
  locks hashed by series name, so a sampler tick and a concurrent
  reader of a different series never contend.
- **Injectable clock.**  Defaults to `utils.faults.monotonic`, so soaks
  driving a `VirtualClock` get virtual-time series for free and tests
  can step time deterministically.
- **Exact cross-host merge** lives in `fleet.merge_timeseries_exports`
  with the same strictness as histogram merges: mismatched kind or
  sampling cadence across hosts raises instead of merging inexactly.

Timestamps are the process's monotonic clock — per-host, not
wall-synchronized.  Cross-host bucket alignment in the merge is exact
on the cadence grid but only *comparable* across hosts to within clock
skew; the merge keeps per-host series verbatim for that reason.
"""
from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from ...utils.faults import monotonic as _monotonic
from .metrics import REGISTRY, MetricsRegistry

__all__ = ["SAMPLED_SERIES", "TimeSeriesStore", "STORE"]


# ---------------------------------------------------------------------------
# The declared-series table.  Every key must resolve in DECLARED_METRICS
# (exact name or a child of a declared family) with a matching kind —
# graftlint rule M004 enforces this statically, the same way M001 pins
# incr()/gauge() call sites to the registry.  Keep this a PLAIN LITERAL:
# the lint AST-parses it without importing the module.
SAMPLED_SERIES: Dict[str, str] = {
    # counters: windows over these answer "how often lately", which the
    # instantaneous registry value cannot
    "training.autosave": "counter",
    "training.rollback": "counter",
    "training.resume": "counter",
    "training.straggler": "counter",
    "checkpoint.write_failed": "counter",
    "dist.host.lost": "counter",
    "xla.compile.count": "counter",
    # gauges: recent level / trend
    "models.training.examples_per_sec": "gauge",
    "training.goodput.frac": "gauge",
    "training.goodput.window_frac": "gauge",
    # histograms: sampled as cumulative <name>.count / <name>.sum
    # counter pairs (rate -> throughput, sum-rate/count-rate -> mean)
    "models.training.step_latency": "histogram",
}

_N_STRIPES = 8


class _Series:
    """One fixed-capacity ring of (t, value) samples.

    Not self-locking — the owning store's stripe lock guards every
    access (`#: guarded-by stripe lock` discipline, same as Histogram's
    stripes carrying their own lock in metrics.py)."""

    __slots__ = ("kind", "ts", "vs", "head", "size", "evicted")

    def __init__(self, kind: str, capacity: int):
        self.kind = kind
        self.ts: List[float] = [0.0] * capacity
        self.vs: List[float] = [0.0] * capacity
        self.head = 0       # next write slot
        self.size = 0
        self.evicted = 0    # samples dropped since creation

    def append(self, t: float, v: float) -> None:
        cap = len(self.ts)
        if self.size == cap:
            self.evicted += 1
        else:
            self.size += 1
        self.ts[self.head] = t
        self.vs[self.head] = v
        self.head = (self.head + 1) % cap

    def points(self) -> List[Tuple[float, float]]:
        """Chronological (t, v) pairs."""
        cap = len(self.ts)
        start = (self.head - self.size) % cap
        out = []
        for i in range(self.size):
            j = (start + i) % cap
            out.append((self.ts[j], self.vs[j]))
        return out


class TimeSeriesStore:
    """Lock-striped ring-buffer store for recent metric history.

    `tick()` is cheap to call once per step: it no-ops until `cadence_s`
    has elapsed since the last sample, then snapshots every series in
    the declared table from the registry.  `record()` appends an
    explicit point outside the sampled table (series created on first
    touch, kind "gauge" unless given).
    """

    def __init__(self, capacity: int = 512, cadence_s: float = 1.0,
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 series: Optional[Mapping[str, str]] = None):
        if capacity < 2:
            raise ValueError("timeseries capacity must be >= 2")
        if cadence_s <= 0:
            raise ValueError("timeseries cadence_s must be > 0")
        self.capacity = capacity
        self.cadence_s = float(cadence_s)
        self._clock = clock if clock is not None else _monotonic
        self._registry = registry if registry is not None else REGISTRY
        self._table = dict(SAMPLED_SERIES if series is None else series)
        self._stripes = [threading.Lock() for _ in range(_N_STRIPES)]
        #: guarded-by the stripe lock for hash(name)
        self._series: List[Dict[str, _Series]] = [
            {} for _ in range(_N_STRIPES)]
        self._tick_lock = threading.Lock()
        self._last_tick: Optional[float] = None  #: guarded-by self._tick_lock

    # ---- write side ----------------------------------------------------
    def _stripe(self, name: str) -> int:
        # hash() is salted per-process for str; series placement only
        # needs to be stable within one process, which it is
        return hash(name) % _N_STRIPES

    def record(self, name: str, value: float, t: Optional[float] = None,
               kind: str = "gauge") -> None:
        """Append one explicit sample (outside the cadence sampler)."""
        t = self._clock() if t is None else float(t)
        i = self._stripe(name)
        with self._stripes[i]:
            s = self._series[i].get(name)
            if s is None:
                s = self._series[i][name] = _Series(kind, self.capacity)
            s.append(t, float(value))

    def tick(self, now: Optional[float] = None) -> bool:
        """Cadence-gated sample of every declared series; returns True
        when a sample was actually taken."""
        now = self._clock() if now is None else float(now)
        with self._tick_lock:
            if (self._last_tick is not None
                    and now - self._last_tick < self.cadence_s):
                return False
            self._last_tick = now
        self.sample(now)
        return True

    def sample(self, now: Optional[float] = None) -> None:
        """Unconditionally snapshot the declared table from the
        registry (counters cumulative, gauges instantaneous, histograms
        as derived .count/.sum cumulative pairs)."""
        now = self._clock() if now is None else float(now)
        counters = gauges = hists = None
        for name, kind in self._table.items():
            if kind == "counter":
                if counters is None:
                    counters = self._registry.counter_values()
                self.record(name, float(counters.get(name, 0)), t=now,
                            kind="counter")
            elif kind == "gauge":
                if gauges is None:
                    gauges = self._registry.gauge_values()
                if name in gauges:
                    self.record(name, gauges[name], t=now, kind="gauge")
            elif kind == "histogram":
                if hists is None:
                    hists = self._registry.histograms()
                n, total = 0, 0.0
                for (hname, _labels), h in hists.items():
                    if hname == name:
                        snap = h.snapshot()
                        n += int(snap["count"])
                        total += float(snap["sum"])
                self.record(name + ".count", float(n), t=now, kind="counter")
                self.record(name + ".sum", total, t=now, kind="counter")
            else:
                raise ValueError(
                    f"sampled series {name!r}: unknown kind {kind!r}")
        self._registry.incr("timeseries.samples")

    # ---- read side -----------------------------------------------------
    def points(self, name: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Chronological samples for one series, optionally restricted
        to the last `window_s` seconds."""
        i = self._stripe(name)
        with self._stripes[i]:
            s = self._series[i].get(name)
            pts = s.points() if s is not None else []
        if window_s is not None:
            now = self._clock() if now is None else float(now)
            lo = now - float(window_s)
            pts = [p for p in pts if p[0] >= lo]
        return pts

    def kind(self, name: str) -> Optional[str]:
        i = self._stripe(name)
        with self._stripes[i]:
            s = self._series[i].get(name)
            return s.kind if s is not None else None

    @staticmethod
    def _increase(pts: Sequence[Tuple[float, float]]) -> Optional[float]:
        """Counter increase over the points, reset-aware: a value drop
        means the counter restarted from zero, so the post-reset value
        is itself an increase (PromQL `increase` semantics, without
        range extrapolation)."""
        if len(pts) < 2:
            return None
        inc = 0.0
        for (_, prev), (_, cur) in zip(pts, pts[1:]):
            inc += (cur - prev) if cur >= prev else cur
        return inc

    def delta(self, name: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Total increase of a counter series (reset-aware) or net
        change of a gauge series over the window; None when fewer than
        two samples cover it."""
        pts = self.points(name, window_s=window_s, now=now)
        if len(pts) < 2:
            return None
        if self.kind(name) == "counter":
            return self._increase(pts)
        return pts[-1][1] - pts[0][1]

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second rate of increase over the window (counter-reset
        aware); None when fewer than two samples cover it."""
        pts = self.points(name, window_s=window_s, now=now)
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        inc = self._increase(pts)
        return None if inc is None else inc / span

    def quantile_over_time(self, name: str, q: float, window_s: float,
                           now: Optional[float] = None) -> Optional[float]:
        """Linear-interpolated quantile of the raw sample VALUES in the
        window (numpy's default "linear" method) — meaningful for gauge
        series; for counters you almost always want rate() first."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        pts = self.points(name, window_s=window_s, now=now)
        if not pts:
            return None
        vs = sorted(v for _, v in pts)
        pos = q * (len(vs) - 1)
        lo = int(math.floor(pos))
        hi = int(math.ceil(pos))
        if lo == hi:
            return vs[lo]
        return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)

    def aligned_window(self, name: str, window_s: float,
                       align_s: Optional[float] = None,
                       now: Optional[float] = None) -> Dict[str, object]:
        """The last `window_s` seconds with both edges snapped DOWN to
        the `align_s` grid (default: the sampling cadence), so repeated
        queries and cross-host comparisons see stable bucket edges
        rather than sliding ones."""
        now = self._clock() if now is None else float(now)
        align = self.cadence_s if align_s is None else float(align_s)
        if align <= 0:
            raise ValueError("align_s must be > 0")
        t_end = math.floor(now / align) * align
        t_start = t_end - float(window_s)
        pts = [p for p in self.points(name) if t_start < p[0] <= t_end]
        return {"t_start": t_start, "t_end": t_end, "align_s": align,
                "points": pts}

    # ---- export / lifecycle --------------------------------------------
    def export(self) -> Dict[str, object]:
        """The wire block served under `/metrics.json` `"timeseries"`:
        cadence, capacity, and every series' chronological points."""
        series: Dict[str, Dict[str, object]] = {}
        for i, lock in enumerate(self._stripes):
            with lock:
                for name, s in self._series[i].items():
                    series[name] = {
                        "kind": s.kind,
                        "evicted": s.evicted,
                        "points": [[round(t, 6), v] for t, v in s.points()],
                    }
        return {"cadence_s": self.cadence_s, "capacity": self.capacity,
                "series": series}

    def reset(self) -> None:
        """Drop every ring and re-arm the cadence gate (tests/soaks)."""
        for i, lock in enumerate(self._stripes):
            with lock:
                self._series[i].clear()
        with self._tick_lock:
            self._last_tick = None


#: The process-wide store `fit_epochs_resumable` ticks once per step and
#: `export_snapshot` serializes; tests construct private stores instead.
STORE = TimeSeriesStore()
