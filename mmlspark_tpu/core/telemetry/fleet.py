"""The fleet telemetry plane: federation, stitching, SLOs, incidents.

PR 6/8 made every process observable; PR 9 made many processes serve one
workload.  This module is the pure (no-HTTP, no-jax) core that turns the
per-process islands into one operable fleet:

* **federation** — :func:`merge_snapshots` folds many replicas'
  ``export_snapshot()`` dicts into one fleet view: counters summed,
  gauges kept per-replica, histograms merged bucket-by-bucket.  The
  merge is EXACT — ``_count``/``_sum`` of the merged series equal the
  sums of the parts — because every declared histogram is pinned to a
  named bucket family (metrics.BUCKET_FAMILIES, graftlint M003), so
  every replica shares identical ``le`` edges; a drifted ladder raises
  instead of producing a silently-wrong merged p99.
* **stitching** — :func:`stitch_spans` assembles one client trace id's
  spans collected from many processes (gateway + replicas) into a single
  parent→child tree; the ``serving.fleet.request`` parentage recorded by
  the PR 9 gateway links the hops.
* **SLOs** — :class:`SLOEngine` evaluates declarative objectives over
  the merged view with multi-window burn-rate alerting (condition must
  hold on BOTH a fast and a slow window) and a
  pending→firing→resolved state machine.  Clock-injectable
  (`utils.faults.monotonic`) so transitions are testable under a
  VirtualClock.
* **incidents** — :class:`FlightRecorder` atomically dumps a post-mortem
  bundle (merged snapshot, stitched traces, recent records, replica
  health, alert states) to ``incidents/<ts>-<reason>/`` when an alert
  starts firing.

The HTTP half (the puller that actually fetches replica snapshots and
the ``/fleet/*`` endpoints) lives in `serving/fleet.py`; this module
never opens a socket.
"""
from __future__ import annotations

import collections
import json
import math
import os
import re
import time
from typing import (Any, Callable, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Tuple)

from ...utils.faults import monotonic as _monotonic
from ...utils.sync import make_lock
from .goodput import detect_straggler
from .metrics import REGISTRY
from .exposition import sanitize_name

__all__ = [
    "parse_hist_key", "merge_histogram_snapshots", "merge_snapshots",
    "merge_timeseries_exports", "merge_goodput_exports",
    "federate_host_snapshots",
    "hist_total", "cum_le", "render_fleet_prometheus", "stitch_spans",
    "SLO", "SLOEngine", "default_slos", "FlightRecorder",
]


# ---------------------------------------------------------------------------
# histogram federation
# ---------------------------------------------------------------------------

_HIST_KEY = re.compile(r'^(?P<name>[^{]+)(?:\{(?P<labels>.*)\})?$')
_LABEL = re.compile(r'(?P<k>[^=,]+)="(?P<v>[^"]*)"')


def parse_hist_key(key: str) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    """Inverse of exposition's snapshot key: ``name{k="v",...}`` →
    (name, sorted label pairs)."""
    m = _HIST_KEY.match(key)
    if m is None:
        return key, ()
    name = m.group("name")
    body = m.group("labels")
    if not body:
        return name, ()
    labels = tuple(sorted((lm.group("k"), lm.group("v"))
                          for lm in _LABEL.finditer(body)))
    return name, labels


def _norm_buckets(buckets: Iterable[Sequence[Any]]
                  ) -> List[Tuple[float, int]]:
    """Snapshot buckets to (le, cum) with "+Inf" (the JSON spelling)
    coerced back to float inf."""
    out: List[Tuple[float, int]] = []
    for le, cum in buckets:
        out.append((math.inf if le == "+Inf" else float(le), int(cum)))
    return out


def _percentile_from_cum(buckets: List[Tuple[float, int]], n: int,
                         q: float) -> Optional[float]:
    """Bucket-interpolated quantile over CUMULATIVE (le, cum) pairs —
    the merged-series twin of Histogram.percentile (same clamping: the
    +Inf bucket reports the last finite edge)."""
    if n <= 0:
        return None
    edges = [le for le, _ in buckets if le != math.inf]
    cums = [c for _, c in buckets]
    counts: List[int] = []
    prev = 0
    for c in cums:
        counts.append(c - prev)
        prev = c
    target = q * n
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target and c > 0:
            if i >= len(edges):
                return edges[-1] if edges else None
            lo = edges[i - 1] if i > 0 else 0.0
            hi = edges[i]
            frac = (target - (cum - c)) / c
            return lo + (hi - lo) * frac
    return edges[-1] if edges else None


def merge_histogram_snapshots(snaps: Sequence[Mapping[str, Any]],
                              key: str = "?") -> Dict[str, Any]:
    """Exact merge of same-ladder histogram snapshots: counts, sums, and
    cumulative buckets add element-wise; percentiles are recomputed from
    the merged cumulative counts.  Mismatched ``le`` edges raise — the
    condition graftlint M003 exists to make impossible."""
    if not snaps:
        return {"count": 0, "sum": 0.0, "buckets": [],
                "p50": None, "p95": None, "p99": None}
    base = _norm_buckets(snaps[0]["buckets"])
    edges = tuple(le for le, _ in base)
    merged = [0] * len(base)
    total_n, total_sum = 0, 0.0
    for snap in snaps:
        bs = _norm_buckets(snap["buckets"])
        if tuple(le for le, _ in bs) != edges:
            raise ValueError(
                f"histogram {key!r}: bucket edges differ across replicas "
                f"— merge would be inexact (declare a bucket family)")
        for i, (_le, cum) in enumerate(bs):
            merged[i] += cum
        total_n += int(snap["count"])
        total_sum += float(snap["sum"])
    buckets = [(le, merged[i]) for i, (le, _c) in enumerate(base)]
    return {
        "count": total_n,
        "sum": total_sum,
        "buckets": buckets,
        "p50": _percentile_from_cum(buckets, total_n, 0.50),
        "p95": _percentile_from_cum(buckets, total_n, 0.95),
        "p99": _percentile_from_cum(buckets, total_n, 0.99),
    }


def merge_timeseries_exports(sources: Mapping[str, Mapping[str, Any]]
                             ) -> Dict[str, Any]:
    """Exact merge of per-host `TimeSeriesStore.export()` blocks, with
    the same strictness as histogram merges: a series whose kind or
    sampling cadence differs across hosts raises instead of merging
    inexactly (the timeseries twin of bucket-edge drift).

    Counter series are summed on the cadence-aligned bucket grid, and
    only on buckets where EVERY host contributed a sample — a partial
    bucket would under-count, which is exactly the silent-wrongness the
    histogram plane refuses.  Gauge series are never summed (same rule
    as `merge_snapshots`); per-host points are kept verbatim under
    ``by_host`` for both kinds.  Timestamps are each host's monotonic
    clock, so the merged grid is exact per host and comparable across
    hosts only to within clock skew.
    """
    series: Dict[str, Dict[str, Any]] = {}
    cadence: Optional[float] = None
    for host in sorted(sources):
        exp = sources[host] or {}
        host_cad = exp.get("cadence_s")
        for name, s in sorted((exp.get("series") or {}).items()):
            ent = series.setdefault(name, {"kind": s.get("kind"),
                                           "cadence_s": host_cad,
                                           "by_host": {}})
            if s.get("kind") != ent["kind"]:
                raise ValueError(
                    f"timeseries {name!r}: kind differs across hosts "
                    f"({ent['kind']!r} vs {s.get('kind')!r}) — merge "
                    f"would be inexact")
            if host_cad != ent["cadence_s"]:
                raise ValueError(
                    f"timeseries {name!r}: sampling cadence differs "
                    f"across hosts ({ent['cadence_s']!r} vs "
                    f"{host_cad!r}) — merge would be inexact")
            ent["by_host"][host] = [
                (float(t), float(v)) for t, v in (s.get("points") or [])]
        if host_cad is not None:
            cadence = host_cad
    for name, ent in series.items():
        if ent["kind"] != "counter":
            ent["merged"] = None
            continue
        cad = float(ent["cadence_s"] or 1.0)
        hosts = set(ent["by_host"])
        buckets: Dict[int, Dict[str, float]] = {}
        for host, pts in ent["by_host"].items():
            for t, v in pts:
                # last sample in a bucket wins (cumulative counters:
                # the latest value subsumes earlier ones)
                buckets.setdefault(int(math.floor(t / cad)), {})[host] = v
        ent["merged"] = [
            [b * cad, sum(by.values())]
            for b, by in sorted(buckets.items()) if set(by) == hosts]
    return {"hosts": sorted(sources), "cadence_s": cadence,
            "series": series}


def merge_goodput_exports(sources: Mapping[str, Mapping[str, Any]],
                          straggler_ratio: float = 2.0,
                          straggler_streak: int = 3) -> Dict[str, Any]:
    """Fold per-host `GoodputLedger.export()` blocks into the federated
    goodput view: per-host summaries, a fleet lost-time table (summed —
    lost seconds are additive across hosts, like counters), the fleet
    goodput fraction (Σ productive / Σ wall), and straggler detection
    over the per-host step timelines.

    A named straggler is surfaced on THIS process's registry — a
    ``training.straggler`` (+ ``.<host>``) counter and the
    ``training.straggler.ratio`` gauge — so the SLOEngine and scrapers
    of the merging process (gateway or soak parent) see it without
    consuming the merged dict."""
    hosts: Dict[str, Any] = {}
    lost: Dict[str, float] = {}
    productive = wall = 0.0
    timelines: Dict[str, Sequence[Mapping[str, Any]]] = {}
    for host in sorted(sources):
        exp = sources[host] or {}
        summ = dict(exp.get("summary") or {})
        steps = list(exp.get("steps") or [])
        hosts[host] = {"summary": summ, "steps": steps}
        for kind, v in (summ.get("lost") or {}).items():
            lost[kind] = lost.get(kind, 0.0) + float(v)
        productive += float(summ.get("productive_s") or 0.0)
        wall += float(summ.get("wall_s") or 0.0)
        timelines[host] = steps
    straggler = detect_straggler(timelines, ratio=straggler_ratio,
                                 streak=straggler_streak)
    if straggler is not None:
        REGISTRY.incr("training.straggler")
        REGISTRY.incr(f"training.straggler.{straggler['host']}")
        REGISTRY.gauge("training.straggler.ratio").set(
            float(straggler["ratio"]))
    return {
        "hosts": hosts,
        "fleet": {
            "productive_s": round(productive, 6),
            "wall_s": round(wall, 6),
            "lost": {k: round(v, 6) for k, v in sorted(lost.items())},
            "goodput_frac": (round(productive / wall, 6)
                             if wall > 0 else None),
        },
        "straggler": straggler,
    }


def merge_snapshots(sources: Mapping[str, Mapping[str, Any]],
                    versions: Optional[Mapping[str, str]] = None
                    ) -> Dict[str, Any]:
    """Fold per-process ``export_snapshot()`` dicts (keyed by replica,
    e.g. ``host:port`` or ``gateway``) into one fleet view:

    * ``counters`` — summed across sources (the fleet event ledger),
      with the per-source split under ``counters_by_replica``;
    * ``gauges`` — per-source only (``{name: {replica: value}}``):
      summing queue depths is meaningful, summing HBM peaks is not, so
      the fleet view keeps the split and lets consumers fold;
    * ``histograms`` — exact bucket-wise merge per ``name{labels}`` key,
      with the per-source snapshots under ``histograms_by_replica``.
    """
    versions = dict(versions or {})
    counters: Dict[str, int] = {}
    counters_by: Dict[str, Dict[str, int]] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    hists_parts: Dict[str, List[Mapping[str, Any]]] = {}
    hists_by: Dict[str, Dict[str, Any]] = {}
    replicas: Dict[str, Dict[str, Any]] = {}
    for rkey, snap in sources.items():
        replicas[rkey] = {"version": versions.get(rkey),
                          "meta": dict(snap.get("meta") or {})}
        cs = snap.get("counters") or {}
        counters_by[rkey] = dict(cs)
        for name, v in cs.items():
            counters[name] = counters.get(name, 0) + int(v)
        for name, v in (snap.get("gauges") or {}).items():
            gauges.setdefault(name, {})[rkey] = float(v)
        hs = snap.get("histograms") or {}
        hists_by[rkey] = {k: dict(s) for k, s in hs.items()}
        for hkey, hsnap in hs.items():
            hists_parts.setdefault(hkey, []).append(hsnap)
    histograms = {hkey: merge_histogram_snapshots(parts, key=hkey)
                  for hkey, parts in sorted(hists_parts.items())}
    merged = {
        "meta": {"replica_count": len(sources),
                 "sources": sorted(sources)},
        "replicas": replicas,
        "counters": counters,
        "counters_by_replica": counters_by,
        "gauges": gauges,
        "histograms": histograms,
        "histograms_by_replica": hists_by,
    }
    # goodput-plane blocks (PR 20) federate whenever any source carries
    # them; sources without one simply don't contribute
    ts_sources = {rkey: snap["timeseries"] for rkey, snap in sources.items()
                  if snap.get("timeseries")}
    if ts_sources:
        merged["timeseries"] = merge_timeseries_exports(ts_sources)
    gp_sources = {rkey: snap["goodput"] for rkey, snap in sources.items()
                  if snap.get("goodput")}
    if gp_sources:
        merged["goodput"] = merge_goodput_exports(gp_sources)
    return merged


def federate_host_snapshots(paths: Mapping[str, Any],
                            versions: Optional[Mapping[str, str]] = None
                            ) -> Dict[str, Any]:
    """`merge_snapshots` over per-HOST snapshot files: ``paths`` maps a
    host id to a JSON file holding that process's ``export_snapshot()``
    dict — the payload a `parallel.distributed.HostTelemetryServer`
    serves at ``/metrics.json`` and tools/dist_soak.py scrapes to disk.
    A missing/torn file drops that host from the view (its ``replicas``
    entry records ``"unreadable": True``) rather than failing the merge:
    a dead host must not take the pod's observability down with it."""
    sources: Dict[str, Mapping[str, Any]] = {}
    unreadable: List[str] = []
    for host_id, path in paths.items():
        try:
            with open(os.fspath(path)) as f:
                sources[str(host_id)] = json.load(f)
        except (OSError, ValueError):
            unreadable.append(str(host_id))
    merged = merge_snapshots(sources, versions)
    for host_id in unreadable:
        merged["replicas"][host_id] = {"unreadable": True}
    return merged


def hist_total(merged: Mapping[str, Any], name: str) -> Dict[str, Any]:
    """One merged snapshot for every label-set of histogram `name` in a
    merged fleet view (``serving.fleet.request.latency`` is labeled per
    outcome; the SLO wants the total)."""
    parts = [snap for hkey, snap in (merged.get("histograms") or {}).items()
             if parse_hist_key(hkey)[0] == name]
    return merge_histogram_snapshots(parts, key=name)


def cum_le(snap: Mapping[str, Any], threshold: float) -> int:
    """Observations ≤ the first bucket edge ≥ `threshold` — the "good
    events" numerator of a latency SLO, resolvable exactly only on
    bucket edges (pick thresholds ON the declared ladder)."""
    for le, cum in _norm_buckets(snap.get("buckets") or ()):
        if le >= threshold:
            return int(cum)
    return int(snap.get("count") or 0)


# ---------------------------------------------------------------------------
# fleet Prometheus rendering
# ---------------------------------------------------------------------------

def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _labels_txt(pairs: Sequence[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{sanitize_name(k)}="{v}"' for k, v in pairs)
    return "{" + body + "}"


def _replica_pairs(merged: Mapping[str, Any], rkey: str
                   ) -> List[Tuple[str, str]]:
    ver = (merged.get("replicas") or {}).get(rkey, {}).get("version")
    pairs = [("replica", rkey)]
    if ver:
        pairs.append(("version", str(ver)))
    return pairs


def _hist_lines(lines: List[str], pn: str, snap: Mapping[str, Any],
                pairs: List[Tuple[str, str]]) -> None:
    for le, cum in _norm_buckets(snap.get("buckets") or ()):
        lines.append(f"{pn}_bucket"
                     f"{_labels_txt(pairs + [('le', _fmt(le))])} {cum}")
    lines.append(f"{pn}_sum{_labels_txt(pairs)} {_fmt(snap['sum'])}")
    lines.append(f"{pn}_count{_labels_txt(pairs)} {snap['count']}")


def render_fleet_prometheus(merged: Mapping[str, Any]) -> str:
    """The merged fleet view in Prometheus text format: every series
    carries ``replica``/``version`` labels for the per-replica split
    plus an unlabeled fleet aggregate (counters and histogram series sum
    exactly; gauges aggregate by sum)."""
    lines: List[str] = []
    counters_by = merged.get("counters_by_replica") or {}
    for name, total in sorted((merged.get("counters") or {}).items()):
        pn = sanitize_name(name)
        lines.append(f"# TYPE {pn} counter")
        for rkey in sorted(counters_by):
            if name in counters_by[rkey]:
                lines.append(f"{pn}{_labels_txt(_replica_pairs(merged, rkey))}"
                             f" {counters_by[rkey][name]}")
        lines.append(f"{pn} {total}")
    for name, per in sorted((merged.get("gauges") or {}).items()):
        pn = sanitize_name(name)
        lines.append(f"# TYPE {pn} gauge")
        for rkey in sorted(per):
            lines.append(f"{pn}{_labels_txt(_replica_pairs(merged, rkey))}"
                         f" {_fmt(per[rkey])}")
        lines.append(f"{pn} {_fmt(sum(per.values()))}")
    hists_by = merged.get("histograms_by_replica") or {}
    typed = set()
    for hkey, snap in sorted((merged.get("histograms") or {}).items()):
        name, labels = parse_hist_key(hkey)
        pn = sanitize_name(name)
        if pn not in typed:
            lines.append(f"# TYPE {pn} histogram")
            typed.add(pn)
        for rkey in sorted(hists_by):
            part = hists_by[rkey].get(hkey)
            if part is not None:
                _hist_lines(lines, pn, part,
                            list(labels) + _replica_pairs(merged, rkey))
        _hist_lines(lines, pn, snap, list(labels))
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# cross-replica trace stitching
# ---------------------------------------------------------------------------

def stitch_spans(trace_id: str,
                 sources: Mapping[str, Sequence[Mapping[str, Any]]]
                 ) -> Dict[str, Any]:
    """Assemble one trace id's spans collected from many processes into
    a single tree.  Spans are deduped by span_id (a replica probed twice
    reports the same records twice), tagged with their ``source``
    process, and nested exactly like spans.span_tree: a span whose
    parent lives in ANOTHER process finds it here — that is the point —
    and only spans whose parent was never recorded anywhere root."""
    seen: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    for rkey in sorted(sources):
        for rec in sources[rkey]:
            if rec.get("trace_id") != trace_id:
                continue
            sid = rec.get("span_id")
            if not sid or sid in seen:
                continue
            seen[sid] = dict(rec, source=rkey)
            order.append(sid)
    flat = [seen[sid] for sid in order]
    nodes = {sid: dict(rec, children=[]) for sid, rec in seen.items()}
    roots: List[Dict[str, Any]] = []
    for node in sorted(nodes.values(), key=lambda r: r.get("t_start", 0.0)):
        parent = nodes.get(node.get("parent_id")) \
            if node.get("parent_id") else None
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    return {"trace_id": trace_id, "sources": sorted(sources),
            "span_count": len(flat), "spans": flat, "tree": roots}


# ---------------------------------------------------------------------------
# the SLO engine
# ---------------------------------------------------------------------------

class SLO:
    """One declarative objective over the merged fleet view.

    `good_total` maps a merged snapshot to cumulative-or-instant
    ``(good_events, total_events)``; the engine turns windows of those
    into an error rate, and ``burn = error_rate / (1 - objective)`` —
    burn 1.0 exactly consumes the error budget over the window.

    * ``kind="cumulative"`` — good/total are monotonic totals (request
      counts); the window error rate is computed from the DELTAS across
      the window.
    * ``kind="instant"`` — good/total are point-in-time readings
      (healthy vs. registered replicas); the window error rate is the
      mean instantaneous ``1 - good/total``.

    The alert fires only when burn exceeds `burn_threshold` on BOTH the
    fast and the slow window (the classic multi-window guard: the fast
    window gives low detection latency, the slow window stops a
    momentary blip from paging), sustained for `for_s`.
    """

    def __init__(self, name: str, objective: float,
                 good_total: Callable[[Mapping[str, Any]],
                                      Tuple[float, float]],
                 kind: str = "cumulative",
                 fast_window_s: float = 30.0,
                 slow_window_s: float = 300.0,
                 burn_threshold: float = 10.0,
                 for_s: float = 0.0,
                 description: str = ""):
        if kind not in ("cumulative", "instant"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not 0.0 < objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if slow_window_s < fast_window_s:
            raise ValueError("slow window must be >= fast window")
        self.name = name
        self.objective = float(objective)
        self.good_total = good_total
        self.kind = kind
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        self.for_s = float(for_s)
        self.description = description


# alert lifecycle: condition seen → pending; held for_s → firing;
# condition clears from firing → resolved; stays clear → inactive
_STATES = ("inactive", "pending", "firing", "resolved")


class SLOEngine:
    """Evaluates SLO burn over a ring of merged-snapshot samples.

    ``observe(merged)`` appends one sample per SLO, recomputes fast and
    slow burn, and advances each alert's state machine, bumping the
    declared ``slo.alert.*`` counters and ``slo.burn_rate.*`` gauges and
    invoking transition listeners (the flight recorder subscribes to
    ``→ firing``).  The clock is injectable so tests drive transitions
    with a VirtualClock."""

    def __init__(self, slos: Sequence[SLO],
                 clock: Callable[[], float] = _monotonic,
                 registry=REGISTRY,
                 max_samples: int = 4096):
        self._slos = list(slos)
        self._clock = clock
        self._registry = registry
        self._lock = make_lock("telemetry.slo.engine")
        #: guarded-by self._lock
        self._samples: Dict[str, "collections.deque"] = {
            s.name: collections.deque(maxlen=max_samples) for s in self._slos}
        self._state: Dict[str, str] = {
            s.name: "inactive" for s in self._slos}  #: guarded-by self._lock
        self._since: Dict[str, float] = {}  #: guarded-by self._lock
        self._last: Dict[str, Dict[str, Any]] = {}  #: guarded-by self._lock
        self._listeners: List[Callable[[SLO, str, str, Dict[str, Any]],
                                       None]] = []  #: guarded-by self._lock

    def on_transition(self, fn: Callable[[SLO, str, str, Dict[str, Any]],
                                         None]) -> None:
        with self._lock:
            self._listeners.append(fn)

    @property
    def slos(self) -> List[SLO]:
        return list(self._slos)

    # ---- window math ---------------------------------------------------

    @staticmethod
    def _window_error(slo: SLO, samples: Sequence[Tuple[float, float, float]],
                      now: float, window_s: float) -> float:
        lo = now - window_s
        inside = [s for s in samples if s[0] >= lo]
        if not inside:
            return 0.0
        if slo.kind == "instant":
            rates = [max(0.0, 1.0 - g / t) for _t0, g, t in inside if t > 0]
            return sum(rates) / len(rates) if rates else 0.0
        # cumulative: delta across the window, anchored at the last
        # sample BEFORE the window when one exists (full-window delta)
        before = [s for s in samples if s[0] < lo]
        anchor = before[-1] if before else inside[0]
        _t, g1, n1 = anchor
        _t2, g2, n2 = inside[-1]
        dn = n2 - n1
        if dn <= 0:
            return 0.0
        dg = g2 - g1
        return max(0.0, 1.0 - dg / dn)

    def _burns(self, slo: SLO, now: float) -> Tuple[float, float]:
        samples = list(self._samples[slo.name])
        budget = 1.0 - slo.objective
        fast = self._window_error(slo, samples, now, slo.fast_window_s)
        slow = self._window_error(slo, samples, now, slo.slow_window_s)
        return fast / budget, slow / budget

    # ---- evaluation ----------------------------------------------------

    def observe(self, merged: Mapping[str, Any],
                now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Feed one merged fleet snapshot; returns the alert list."""
        now = self._clock() if now is None else float(now)
        transitions: List[Tuple[SLO, str, str, Dict[str, Any]]] = []
        with self._lock:
            for slo in self._slos:
                try:
                    good, total = slo.good_total(merged)
                except Exception:
                    continue  # a malformed snapshot must not kill the loop
                self._samples[slo.name].append(
                    (now, float(good), float(total)))
                burn_fast, burn_slow = self._burns(slo, now)
                cond = (burn_fast >= slo.burn_threshold
                        and burn_slow >= slo.burn_threshold)
                state = self._state[slo.name]
                if state == "inactive" and cond:
                    state = "pending"
                    self._since[slo.name] = now
                    transitions.append((slo, "inactive", "pending", {}))
                elif state == "pending" and not cond:
                    state = "inactive"
                    self._since.pop(slo.name, None)
                elif state == "resolved":
                    if cond:
                        state = "pending"
                        self._since[slo.name] = now
                        transitions.append((slo, "resolved", "pending", {}))
                    else:
                        state = "inactive"
                if state == "pending" and cond and \
                        now - self._since.get(slo.name, now) >= slo.for_s:
                    state = "firing"
                    transitions.append((slo, "pending", "firing", {}))
                elif state == "firing" and not cond:
                    state = "resolved"
                    self._since.pop(slo.name, None)
                    transitions.append((slo, "firing", "resolved", {}))
                self._state[slo.name] = state
                self._last[slo.name] = {
                    "slo": slo.name,
                    "state": state,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "objective": slo.objective,
                    "burn_threshold": slo.burn_threshold,
                    "fast_window_s": slo.fast_window_s,
                    "slow_window_s": slo.slow_window_s,
                    "since": self._since.get(slo.name),
                    "good": good,
                    "total": total,
                    "description": slo.description,
                }
                self._registry.gauge(
                    f"slo.burn_rate.{slo.name}").set(burn_fast)
            listeners = list(self._listeners)
            alerts = [dict(self._last[s.name]) for s in self._slos
                      if s.name in self._last]
            # snapshot per-transition detail while still under the lock;
            # listeners run outside it (they may call back into us)
            transitions = [
                (slo, old, new, dict(self._last.get(slo.name, {}), **info))
                for slo, old, new, info in transitions]
        for slo, old, new, info in transitions:
            self._registry.incr(f"slo.alert.{new}")
            self._registry.incr(f"slo.alert.{new}.{slo.name}")
            for fn in listeners:
                try:
                    fn(slo, old, new, info)
                except Exception:
                    pass  # a listener must never break evaluation
        return alerts

    def alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(self._last[s.name]) for s in self._slos
                    if s.name in self._last]

    def state(self, name: str) -> str:
        with self._lock:
            return self._state.get(name, "inactive")


def default_slos(latency_threshold_s: float = 0.31622776601683794,
                 fast_window_s: float = 30.0,
                 slow_window_s: float = 300.0,
                 burn_threshold: float = 10.0) -> List[SLO]:
    """The stock fleet objectives.  The latency threshold defaults to
    the 10^-0.5 s edge of the latency bucket family — latency SLOs only
    resolve exactly ON a declared edge."""

    def availability(m: Mapping[str, Any]) -> Tuple[float, float]:
        g = m.get("gauges") or {}
        healthy = sum((g.get("serving.fleet.healthy") or {}).values())
        total = sum((g.get("serving.fleet.replicas") or {}).values())
        return healthy, total

    def latency(m: Mapping[str, Any]) -> Tuple[float, float]:
        snap = hist_total(m, "serving.fleet.request.latency")
        return float(cum_le(snap, latency_threshold_s)), \
            float(snap["count"])

    def deadline(m: Mapping[str, Any]) -> Tuple[float, float]:
        c = m.get("counters") or {}
        missed = sum(v for k, v in c.items()
                     if k == "serving.fleet.deadline_expired"
                     or k == "serving.deadline_expired"
                     or k == "batcher.deadline_expired")
        snap = hist_total(m, "serving.fleet.request.latency")
        total = float(snap["count"])
        return max(0.0, total - missed), total

    return [
        SLO("availability", 0.999, availability, kind="instant",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            burn_threshold=burn_threshold,
            description="routable replicas / registered replicas"),
        SLO("latency_p99", 0.99, latency, kind="cumulative",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            burn_threshold=burn_threshold,
            description=f"fleet requests <= {latency_threshold_s:.3g}s"),
        SLO("deadline_miss", 0.999, deadline, kind="cumulative",
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            burn_threshold=burn_threshold,
            description="requests not expired past their deadline"),
    ]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class FlightRecorder:
    """Atomic post-mortem bundles under ``<root>/incidents/``.

    ``dump()`` writes every artifact into a hidden temp directory and
    renames it into place — a crash mid-dump leaves only a ``.tmp-*``
    turd, never a half-readable incident — then prunes oldest bundles
    beyond `max_bundles` so an alert flapping all night cannot fill the
    disk."""

    def __init__(self, root: str, max_bundles: int = 16):
        self.root = os.path.join(root, "incidents")
        self.max_bundles = int(max_bundles)
        self._lock = make_lock("telemetry.flight.recorder")
        self._seq = 0  #: guarded-by self._lock

    def dump(self, reason: str,
             merged: Optional[Mapping[str, Any]] = None,
             traces: Optional[Mapping[str, Any]] = None,
             records: Optional[Sequence[Any]] = None,
             health: Optional[Mapping[str, Any]] = None,
             alerts: Optional[Sequence[Mapping[str, Any]]] = None) -> str:
        safe = sanitize_name(reason) or "incident"
        with self._lock:
            self._seq += 1
            stamp = time.strftime("%Y%m%dT%H%M%S")
            name = f"{stamp}-{self._seq:03d}-{safe}"
            final = os.path.join(self.root, name)
            tmp = os.path.join(self.root, f".tmp-{name}")
            os.makedirs(tmp, exist_ok=True)
            artifacts = {
                "snapshot.json": merged,
                "traces.json": traces,
                "records.json": list(records) if records else None,
                "health.json": health,
                "alerts.json": list(alerts) if alerts else None,
            }
            written = []
            for fname, obj in artifacts.items():
                if obj is None:
                    continue
                with open(os.path.join(tmp, fname), "w") as f:
                    json.dump(obj, f, indent=2, default=repr)
                written.append(fname)
            manifest = {"reason": reason, "created": stamp,
                        "files": sorted(written)}
            with open(os.path.join(tmp, "MANIFEST.json"), "w") as f:
                json.dump(manifest, f, indent=2)
            os.rename(tmp, final)
            self._registry_incr()
            self._prune_locked()
        return final

    def _registry_incr(self) -> None:
        REGISTRY.incr("fleet.incident")

    def _prune_locked(self) -> None:
        try:
            bundles = sorted(d for d in os.listdir(self.root)
                             if not d.startswith("."))
        except OSError:
            return
        for stale in bundles[:-self.max_bundles] \
                if len(bundles) > self.max_bundles else []:
            path = os.path.join(self.root, stale)
            try:
                for fn in os.listdir(path):
                    os.unlink(os.path.join(path, fn))
                os.rmdir(path)
            except OSError:
                pass

    def bundles(self) -> List[str]:
        try:
            return sorted(os.path.join(self.root, d)
                          for d in os.listdir(self.root)
                          if not d.startswith("."))
        except OSError:
            return []
