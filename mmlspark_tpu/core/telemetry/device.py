"""Device-level observability: the XLA compile sentry, HBM memory
gauges, and opt-in `jax.profiler` trace annotations.

Everything host-side in this package watches OUR code; this module
watches the runtime underneath it.  Three concerns:

* **Compile sentry** — `track_compiles()` registers a `jax.monitoring`
  event-duration listener (fires synchronously on the compiling thread)
  that records every XLA compile as an `xla.compile` span — a child of
  the active trace when one is open, so a serving request that triggered
  a compile shows it in `/trace/<id>` — plus an `xla.compile.latency`
  histogram observation and an `xla.compile.count` bump.  After the
  caller DECLARES warmup over (`SENTRY.end_warmup()`), every further
  compile is flagged as a steady-state recompile: `xla.compile.hot_path`
  counter + WARNING log.  This jax version's monitoring events carry no
  function/shape metadata, so naming the triggering shape is the job of
  `watch_compiles(fn, name)`: a transparent wrapper around a jitted
  callable that detects a compile during a call (`_cache_size()` delta,
  falling back to the sentry's global compile count) and, in steady
  state, emits a loud `log_verb` record + WARNING naming the argument
  shapes that forced it (`float32[8,224,224,3]`), bumping the per-entry
  `xla.compile.hot_path.<name>` family.

* **Memory gauges** — `sample_device_memory()` folds
  `device.memory_stats()` across local devices into
  `device.hbm.bytes_in_use` / `device.hbm.peak_bytes` and counts
  `client.live_buffers()` into `device.live_buffer_count`.  Backends
  without memory_stats (CPU CI) skip the HBM gauges and keep the buffer
  count — a graceful no-op, never an exception.  The sampler is PASSIVE:
  if jax is not imported, or imported but its backend never initialized,
  sampling returns {} rather than being the thing that grabs a device.
  `start_memory_sampler(interval_s)` runs it on a daemon thread;
  `ServingServer` best-effort samples on every `/metrics` scrape.

* **Device annotations** — `enable_device_annotations()` arms the span
  layer so `span()` additionally enters a `jax.profiler.TraceAnnotation`
  for matching span names (`training.step`, `pipeline.<stage>`, ...),
  and `device_annotation(name)` gives already-measured sites
  (`feed._device_put`) the same opt-in wrapper.  Off by default: on real
  hardware under a profiler capture the device timeline then carries our
  span names.

This module imports no jax at module scope — the telemetry package must
stay importable (and `/metrics` servable) in processes that never touch
a device.
"""
from __future__ import annotations

import contextlib
import sys
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

from . import spans as _spans
from .goodput import LEDGER
from .metrics import REGISTRY
from .records import log_verb, logger

__all__ = ["CompileSentry", "SENTRY", "track_compiles", "watch_compiles",
           "describe_abstract_shapes", "sample_device_memory",
           "MemorySampler", "start_memory_sampler",
           "enable_device_annotations", "device_annotation",
           "DEFAULT_ANNOTATION_PREFIXES"]

# the one monitoring event that means "XLA produced an executable";
# jaxpr tracing / MLIR lowering durations ride the same listener but are
# phases of the same compile, not separate compiles
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"


def describe_abstract_shapes(args: Iterable[Any],
                             kwargs: Optional[Dict[str, Any]] = None,
                             limit: int = 8) -> str:
    """'float32[8,224,224,3], int32[8]' for the array-like leaves among
    a call's top-level arguments — the shape signature a recompile keys
    on.  Non-array arguments (pytrees of params, static config) are
    skipped: the data batch is what changes shape in practice."""
    parts = []
    values = list(args) + list((kwargs or {}).values())
    for v in values:
        shape = getattr(v, "shape", None)
        dtype = getattr(v, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            dims = ",".join(str(int(d)) for d in shape)
        except (TypeError, ValueError):
            dims = str(shape)
        parts.append(f"{dtype}[{dims}]")
        if len(parts) >= limit:
            parts.append("...")
            break
    return ", ".join(parts) if parts else "<no array args>"


class CompileSentry:
    """Process-wide compile watcher.  Starts in WARMUP: compiles are
    recorded (span + histogram + count) but expected.  After
    `end_warmup()` every compile is a steady-state recompile — the exact
    hazard `tpu_model.pad_to_batch` exists to prevent — and is flagged
    loudly.  `reset()` returns to warmup (tests, or a planned
    reconfiguration that legitimately recompiles)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._installed = False
        self._listener_active = False
        self._steady = False
        self._compiles = 0

    # ---- state ---------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Compiles seen by the monitoring listener (0 when unavailable)."""
        with self._lock:
            return self._compiles

    @property
    def listener_active(self) -> bool:
        with self._lock:
            return self._listener_active

    @property
    def in_warmup(self) -> bool:
        with self._lock:
            return not self._steady

    def end_warmup(self) -> None:
        """Declare warmup over: from here, any compile is a hot-path
        recompile and gets flagged."""
        with self._lock:
            self._steady = True

    def reset(self) -> None:
        with self._lock:
            self._steady = False

    @contextlib.contextmanager
    def warmup(self):
        """Compiles inside the block are warmup; steady-state flagging
        (re-)arms when it exits."""
        with self._lock:
            self._steady = False
        try:
            yield self
        finally:
            self.end_warmup()

    # ---- installation --------------------------------------------------
    def install(self) -> "CompileSentry":
        """Idempotently register the jax.monitoring listener.  Without
        jax (or without the monitoring API) the sentry still works in
        wrapper-only mode: `watch_compiles` call sites detect compiles
        via `_cache_size()` deltas."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                self._on_event_duration)
        except Exception:
            return self
        with self._lock:
            self._listener_active = True
        return self

    def _on_event_duration(self, event: str, duration: float,
                           **_kw: Any) -> None:
        # fires synchronously on the thread running the compile, so
        # current_context() attributes the span to the request/step that
        # triggered it
        if not event.endswith(_COMPILE_EVENT_SUFFIX):
            return
        with self._lock:
            self._compiles += 1
            steady = self._steady
        phase = "steady" if steady else "warmup"
        try:
            REGISTRY.incr("xla.compile.count")
            REGISTRY.histogram("xla.compile.latency").observe(float(duration))
            _spans.record_span("xla.compile", _spans.current_context(),
                               float(duration), phase=phase)
            # a compile observed after training started is wall the run
            # can never get back — the goodput ledger drops this until
            # its first recorded step, so warmup stays unattributed
            LEDGER.note_lost("recompile", float(duration))
            if steady:
                REGISTRY.incr("xla.compile.hot_path")
                logger.warning(
                    "xla.compile.hot_path: steady-state XLA recompile "
                    "(%.3fs backend compile) — a shape/dtype the warmup "
                    "never saw reached a jitted function", duration)
        except Exception:
            # a telemetry listener must never break a compile
            pass

    # ---- wrapper-side reporting ----------------------------------------
    def note_traced_compile(self, name: str, args: tuple,
                            kwargs: Dict[str, Any]) -> None:
        """A `watch_compiles` wrapper saw its function compile during a
        call.  In warmup this is expected (the listener already counted
        it); in steady state, name the triggering shape loudly."""
        with self._lock:
            steady = self._steady
            listener = self._listener_active
        if not steady:
            return
        shape = describe_abstract_shapes(args, kwargs)
        REGISTRY.incr(f"xla.compile.hot_path.{name}")
        if not listener:
            # no monitoring API: the wrapper is the only counter
            REGISTRY.incr("xla.compile.count")
            REGISTRY.incr("xla.compile.hot_path")
        with log_verb(self, "hot_path_recompile", fn=name, shape=shape):
            pass
        logger.warning(
            "xla.compile.hot_path: %s recompiled in steady state for %s "
            "— pad or bucket inputs so serving/training reuses the "
            "warmed executable", name, shape)


SENTRY = CompileSentry()


def track_compiles() -> CompileSentry:
    """Arm the process-wide compile sentry (idempotent) and return it.
    Call once before warmup; call `.end_warmup()` when the shapes you
    intend to serve/train have all compiled."""
    return SENTRY.install()


class _WatchedFunction:
    """Transparent proxy over a jitted callable that reports compiles to
    the sentry with shape attribution.  Attribute access (`.lower`,
    `.clear_cache`, ...) passes through, so call sites that treat the
    value as a PjitFunction keep working."""

    __slots__ = ("_fn", "_name", "_sentry")

    def __init__(self, fn, name: str, sentry: CompileSentry):
        self._fn = fn
        self._name = name
        self._sentry = sentry

    @property
    def __wrapped__(self):
        return self._fn

    def __getattr__(self, item):
        return getattr(self._fn, item)

    def _marker(self) -> Tuple[str, int]:
        cache_size = getattr(self._fn, "_cache_size", None)
        if cache_size is not None:
            try:
                return ("cache", int(cache_size()))
            except Exception:
                pass
        return ("global", self._sentry.compile_count)

    def __call__(self, *args, **kwargs):
        kind_before, before = self._marker()
        out = self._fn(*args, **kwargs)
        kind_after, after = self._marker()
        if kind_after == kind_before and after > before:
            self._sentry.note_traced_compile(self._name, args, kwargs)
        return out

    def __repr__(self) -> str:
        return f"watch_compiles({self._fn!r}, name={self._name!r})"


def watch_compiles(fn, name: str,
                   sentry: Optional[CompileSentry] = None):
    """Wrap a jitted callable so steady-state recompiles are attributed
    to `name` and the triggering argument shapes.  Arms the sentry's
    monitoring listener as a side effect (the wrapper and the listener
    are two halves of one mechanism: the listener times and counts, the
    wrapper names)."""
    s = sentry if sentry is not None else SENTRY
    s.install()
    return _WatchedFunction(fn, name, s)


# ---- memory gauges --------------------------------------------------------

def _jax_if_initialized():
    """The imported jax module, or None when jax is absent OR its
    backend was never initialized — a metrics scrape must stay passive
    and never be the call that claims a device."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge
        backends = getattr(xla_bridge, "_backends", None)
        if backends is not None and not backends:
            return None
    except Exception:
        pass
    return jax


def sample_device_memory(devices=None) -> Dict[str, int]:
    """One best-effort sample of device memory into the gauges.

    Returns the sampled values ({} when jax/backend is unavailable):
    `hbm_bytes_in_use` / `hbm_peak_bytes` summed across local devices
    where the backend reports `memory_stats()` (TPU/GPU; CPU returns
    None and the HBM gauges are simply not written), and
    `live_buffer_count` from each client's `live_buffers()` (works on
    every backend; falls back to `jax.live_arrays()`)."""
    jax = _jax_if_initialized()
    if jax is None:
        return {}
    try:
        devs = list(devices) if devices is not None else jax.local_devices()
    except Exception:
        return {}
    out: Dict[str, int] = {}
    bytes_in_use = peak_bytes = 0
    have_stats = False
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        have_stats = True
        used = int(stats.get("bytes_in_use", 0))
        bytes_in_use += used
        peak_bytes += int(stats.get("peak_bytes_in_use", used))
    if have_stats:
        REGISTRY.gauge("device.hbm.bytes_in_use").set(bytes_in_use)
        REGISTRY.gauge("device.hbm.peak_bytes").set(peak_bytes)
        out["hbm_bytes_in_use"] = bytes_in_use
        out["hbm_peak_bytes"] = peak_bytes
    n_buffers: Optional[int] = None
    try:
        clients = {id(d.client): d.client for d in devs}
        n_buffers = sum(len(c.live_buffers()) for c in clients.values())
    except Exception:
        try:
            n_buffers = len(jax.live_arrays())
        except Exception:
            n_buffers = None
    if n_buffers is not None:
        REGISTRY.gauge("device.live_buffer_count").set(n_buffers)
        out["live_buffer_count"] = n_buffers
    return out


class MemorySampler:
    """Daemon thread sampling device memory every `interval_s`.  Also a
    context manager: `with MemorySampler(5.0): ...`."""

    def __init__(self, interval_s: float = 5.0, devices=None):
        self.interval_s = float(interval_s)
        self._devices = devices
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MemorySampler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="device-memory-sampler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                sample_device_memory(self._devices)
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    def __enter__(self) -> "MemorySampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def start_memory_sampler(interval_s: float = 5.0,
                         devices=None) -> MemorySampler:
    return MemorySampler(interval_s, devices).start()


# ---- device annotations ---------------------------------------------------

# the stage spans worth seeing on a device timeline: the training step,
# the h2d transfer, and the host-pipeline stages (recorded as
# `pipeline.<stage>` spans and annotated as such)
DEFAULT_ANNOTATION_PREFIXES: Tuple[str, ...] = (
    "training.step", "feed.transfer", "io.pipeline", "pipeline.")


def enable_device_annotations(
        enabled: bool = True,
        prefixes: Tuple[str, ...] = DEFAULT_ANNOTATION_PREFIXES) -> bool:
    """Opt in (or out) of wrapping matching spans in
    `jax.profiler.TraceAnnotation` so a real profiler capture shows our
    span names on the device timeline.  Returns True when armed."""
    if not enabled:
        _spans.set_annotation_hook(None, ())
        return False
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        _spans.set_annotation_hook(None, ())
        return False
    _spans.set_annotation_hook(TraceAnnotation, tuple(prefixes))
    return True


def device_annotation(name: str):
    """A TraceAnnotation context for `name` when annotations are armed
    and the name matches, else a no-op context — for already-measured
    sites (`feed._device_put`, pipeline workers) whose spans go through
    `record_span` and so never pass through `span()`'s hook."""
    factory, prefixes = _spans.get_annotation_hook()
    if factory is None or not prefixes or not name.startswith(prefixes):
        return contextlib.nullcontext()
    try:
        return factory(name)
    except Exception:
        return contextlib.nullcontext()
