"""Unified observability layer: records, counters, gauges, histograms,
spans, and exposition — one package, one registry.

Grown from the single-module `core/telemetry.py` (flat event counters +
verb records); the historical surface is preserved verbatim:

* ``incr`` / ``counters`` / ``reset_counters`` — the PR-4 event-counter
  ledger, now backed by :data:`metrics.REGISTRY` so every counter a
  fault/shed/breaker path bumps shows up in ``/metrics`` and
  ``export_snapshot()`` with zero changes at the call sites.
* ``log_verb`` / ``recent_records`` / ``clear_records`` — stage-verb
  JSON records (:mod:`.records`).
* ``StopWatch`` — re-export of the ONE canonical
  :class:`mmlspark_tpu.utils.stopwatch.StopWatch` (the duplicate that
  lived here was merged into it; identity is pinned by tests).

New surface (see docs/observability.md):

* spans — ``span()``, ``use_trace()``, ``record_span()``,
  ``trace_headers()`` / ``extract_trace()`` for X-Trace-Id propagation,
  ``get_trace()`` / ``span_tree()`` behind ``/trace/<id>``.
* metrics — ``histogram(name)`` / ``gauge(name)`` on the process
  registry; names follow ``layer.component.metric`` and must be
  declared in :data:`metrics.DECLARED_METRICS` (CI-linted).
* exposition — ``render_prometheus()`` (``/metrics``),
  ``export_snapshot()`` (bench / chaos_soak / obs_report),
  ``render_chrome_trace()`` (``/trace.json`` → Perfetto).
* device — ``track_compiles()`` / ``watch_compiles()`` (the XLA compile
  sentry), ``sample_device_memory()`` / ``start_memory_sampler()`` (HBM
  + live-buffer gauges), ``enable_device_annotations()`` (opt-in
  ``jax.profiler.TraceAnnotation`` on stage spans).
* goodput plane — ``STORE`` (:class:`timeseries.TimeSeriesStore`,
  bounded recent history with rate/delta/quantile-over-time) and
  ``LEDGER`` (:class:`goodput.GoodputLedger`, per-step timelines +
  lost-time attribution), federated by ``merge_timeseries_exports`` /
  ``merge_goodput_exports`` and served in the ``timeseries`` /
  ``goodput`` blocks of ``export_snapshot()``.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from ...utils.stopwatch import StopWatch
from .metrics import (
    BUCKET_FAMILIES,
    BYTE_BUCKETS,
    DECLARED_METRICS,
    FILL_BUCKETS,
    Gauge,
    HISTOGRAM_FAMILY,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    buckets_for,
    default_buckets,
    is_declared,
)
from .records import clear_records, log_verb, logger, recent_records
from .spans import (
    clear_spans,
    current_context,
    current_trace_id,
    extract_trace,
    get_trace,
    recent_spans,
    record_span,
    span,
    span_tree,
    trace_headers,
    use_trace,
)
from .exposition import (
    export_snapshot,
    format_latency_table,
    format_span_tree,
    render_chrome_trace,
    render_prometheus,
)
from .fleet import (
    FlightRecorder,
    SLO,
    SLOEngine,
    default_slos,
    merge_snapshots,
    merge_goodput_exports,
    merge_histogram_snapshots,
    merge_timeseries_exports,
    render_fleet_prometheus,
    stitch_spans,
)
from .goodput import (
    GoodputLedger,
    LEDGER,
    LOST_KINDS,
    StepTimeline,
    detect_straggler,
)
from .timeseries import SAMPLED_SERIES, STORE, TimeSeriesStore
from .device import (
    SENTRY,
    CompileSentry,
    MemorySampler,
    device_annotation,
    enable_device_annotations,
    sample_device_memory,
    start_memory_sampler,
    track_compiles,
    watch_compiles,
)

__all__ = [
    # counters (historical surface, registry-backed)
    "incr", "counters", "reset_counters",
    # records
    "log_verb", "recent_records", "clear_records", "logger",
    # stopwatch
    "StopWatch",
    # metrics
    "REGISTRY", "MetricsRegistry", "Gauge", "Histogram", "gauge",
    "histogram", "default_buckets", "BYTE_BUCKETS", "FILL_BUCKETS",
    "BUCKET_FAMILIES", "HISTOGRAM_FAMILY", "buckets_for",
    "DECLARED_METRICS", "is_declared",
    # spans
    "span", "record_span", "use_trace", "current_context",
    "current_trace_id", "trace_headers", "extract_trace", "get_trace",
    "span_tree", "recent_spans", "clear_spans",
    # exposition
    "render_prometheus", "export_snapshot", "render_chrome_trace",
    "format_span_tree", "format_latency_table",
    # fleet federation (merge / stitch / SLO / incidents)
    "merge_snapshots", "merge_histogram_snapshots",
    "merge_timeseries_exports", "merge_goodput_exports",
    "render_fleet_prometheus", "stitch_spans", "SLO", "SLOEngine",
    "default_slos", "FlightRecorder",
    # goodput plane (timeseries engine + lost-time ledger, PR 20)
    "TimeSeriesStore", "STORE", "SAMPLED_SERIES",
    "GoodputLedger", "StepTimeline", "LEDGER", "LOST_KINDS",
    "detect_straggler",
    # device (compile sentry, memory gauges, annotations)
    "SENTRY", "CompileSentry", "track_compiles", "watch_compiles",
    "sample_device_memory", "MemorySampler", "start_memory_sampler",
    "enable_device_annotations", "device_annotation",
]


def incr(name: str, n: int = 1) -> None:
    """Bump a named event counter (dotted names: 'serving.shed')."""
    REGISTRY.incr(name, n)


def counters(prefix: Optional[str] = None) -> Dict[str, int]:
    """Snapshot the event counters, optionally filtered by name prefix."""
    return REGISTRY.counter_values(prefix)


def reset_counters(prefix: Optional[str] = None) -> None:
    """Zero the counters (tests); with `prefix`, only matching names."""
    REGISTRY.reset_counters(prefix)


def gauge(name: str) -> Gauge:
    """The process-registry gauge `name` (created on first touch)."""
    return REGISTRY.gauge(name)


def histogram(name: str, boundaries: Optional[Sequence[float]] = None,
              **labels: str) -> Histogram:
    """The process-registry histogram `name` (first touch fixes the
    bucket ladder for the whole labeled family)."""
    return REGISTRY.histogram(name, boundaries, **labels)
