"""Dapper-style spans: trace-id / span-id / parent-id wall-time records.

A **trace** is one logical request's causal tree; a **span** is one
timed operation inside it.  The current (trace_id, span_id) pair lives
in a `contextvars.ContextVar` — per-thread by construction (each thread
starts from an empty context), and correctly scoped under async/greenlet
frameworks that propagate contexts.  Crossing an EXPLICIT thread
boundary (a serving batch loop picking up a held request, a stream pool
worker) re-activates the recorded pair via `use_trace(ctx)`; crossing a
PROCESS boundary rides the `X-Trace-Id` / `X-Span-Id` HTTP headers
(`trace_headers()` injects on the client, `extract_trace()` continues on
the server).

Finished spans land in a bounded ring (`recent_spans`) and a bounded
per-trace index (`get_trace`/`span_tree` — what `/trace/<id>` serves).
Both are capped, so always-on span recording cannot grow host memory;
the caps drop OLDEST whole traces first (a live investigation wants the
most recent requests).
"""
from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["span", "record_span", "use_trace", "current_context",
           "current_trace_id", "trace_headers", "extract_trace",
           "get_trace", "span_tree", "recent_spans", "clear_spans",
           "set_annotation_hook", "get_annotation_hook",
           "MAX_SPANS", "MAX_TRACES", "MAX_SPANS_PER_TRACE"]

MAX_SPANS = 8192          # global recent-span ring
MAX_TRACES = 512          # distinct trace ids indexed for /trace/<id>
MAX_SPANS_PER_TRACE = 2048

# (trace_id, span_id) of the CURRENT span, or None outside any trace
_CTX: "contextvars.ContextVar[Optional[Tuple[str, str]]]" = \
    contextvars.ContextVar("mmlspark_tpu_trace", default=None)

_LOCK = threading.Lock()
_SPANS: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=MAX_SPANS)
_TRACES: "collections.OrderedDict[str, List[Dict[str, Any]]]" = \
    collections.OrderedDict()


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


# Optional device-annotation hook (set by telemetry.device): a factory of
# context managers (jax.profiler.TraceAnnotation) plus the span-name
# prefixes it applies to.  When armed, span() additionally enters an
# annotation for matching names so the device timeline in a real
# profiler capture carries our span names.  Kept here (not in device.py)
# so span() stays jax-import-free: the factory is injected, never looked
# up.
_ANNOTATION_FACTORY = None
_ANNOTATION_PREFIXES: Tuple[str, ...] = ()


def set_annotation_hook(factory, prefixes: Tuple[str, ...] = ()) -> None:
    """Arm (or with factory=None disarm) the device-annotation hook."""
    global _ANNOTATION_FACTORY, _ANNOTATION_PREFIXES
    _ANNOTATION_FACTORY = factory
    _ANNOTATION_PREFIXES = tuple(prefixes)


def get_annotation_hook():
    return _ANNOTATION_FACTORY, _ANNOTATION_PREFIXES


def _annotation_for(name: str):
    if _ANNOTATION_FACTORY is None or not _ANNOTATION_PREFIXES:
        return None
    if not name.startswith(_ANNOTATION_PREFIXES):
        return None
    try:
        return _ANNOTATION_FACTORY(name)
    except Exception:
        return None


def current_context() -> Optional[Tuple[str, str]]:
    """The active (trace_id, span_id), or None."""
    return _CTX.get()


def current_trace_id() -> Optional[str]:
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else None


def _store(rec: Dict[str, Any]) -> None:
    with _LOCK:
        _SPANS.append(rec)
        tid = rec["trace_id"]
        spans = _TRACES.get(tid)
        if spans is None:
            while len(_TRACES) >= MAX_TRACES:
                _TRACES.popitem(last=False)
            spans = _TRACES[tid] = []
        if len(spans) < MAX_SPANS_PER_TRACE:
            spans.append(rec)


class _Span:
    """Handle yielded by span(): ids plus a mutable attr dict the body
    can annotate (outcome, sizes) before the record is stored."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], attrs: Dict[str, Any]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = attrs


@contextlib.contextmanager
def span(name: str, parent_ctx: Optional[Tuple[str, str]] = None,
         **attrs: Any):
    """Open a span: child of the current context (or of `parent_ctx`,
    e.g. one extracted from request headers); a fresh trace root when
    neither exists.  Wall time and a raised exception's type are
    captured; the exception propagates."""
    parent = parent_ctx if parent_ctx is not None else _CTX.get()
    trace_id = parent[0] if parent else _new_id()
    span_id = _new_id()
    sp = _Span(name, trace_id, span_id,
               parent[1] if parent else None, dict(attrs))
    token = _CTX.set((trace_id, span_id))
    annotation = _annotation_for(name)
    t_start = time.time()
    t0 = time.perf_counter()
    err: Optional[str] = None
    try:
        if annotation is not None:
            with annotation:
                yield sp
        else:
            yield sp
    except BaseException as e:  # noqa: BLE001 — recorded, then re-raised
        err = type(e).__name__
        raise
    finally:
        _CTX.reset(token)
        rec: Dict[str, Any] = {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_id": sp.parent_id,
            "t_start": t_start,
            "wall_s": round(time.perf_counter() - t0, 6),
            "tid": threading.get_ident(),
        }
        if err:
            rec["error"] = err
        if sp.attrs:
            rec["attrs"] = sp.attrs
        _store(rec)


def record_span(name: str, ctx: Optional[Tuple[str, str]], wall_s: float,
                **attrs: Any) -> Dict[str, Any]:
    """Record an already-measured span as a child of `ctx` — the
    cross-thread shape (a batch loop attributing queue wait to the
    handler thread's request span) where a context manager can't wrap
    the producer.  With ctx=None the span roots a fresh trace (the
    compile sentry recording an XLA compile that fired outside any
    request)."""
    rec: Dict[str, Any] = {
        "name": name,
        "trace_id": ctx[0] if ctx is not None else _new_id(),
        "span_id": _new_id(),
        "parent_id": ctx[1] if ctx is not None else None,
        "t_start": time.time() - wall_s,
        "wall_s": round(float(wall_s), 6),
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = dict(attrs)
    _store(rec)
    return rec


@contextlib.contextmanager
def use_trace(ctx: Optional[Tuple[str, str]]):
    """Re-activate a recorded (trace_id, span_id) on THIS thread (the
    explicit thread-hop propagation).  None is a no-op, so call sites
    can pass a request's maybe-absent context unconditionally."""
    if ctx is None:
        yield
        return
    token = _CTX.set((ctx[0], ctx[1]))
    try:
        yield
    finally:
        _CTX.reset(token)


# ---- HTTP propagation ----------------------------------------------------

TRACE_HEADER = "X-Trace-Id"
SPAN_HEADER = "X-Span-Id"


def trace_headers(headers: Optional[Dict[str, str]] = None
                  ) -> Dict[str, str]:
    """Copy of `headers` with the current trace context injected (the
    client half of propagation).  Outside any trace, or when the caller
    already set the headers, the copy is returned unchanged."""
    out = dict(headers or {})
    ctx = _CTX.get()
    if ctx is not None:
        out.setdefault(TRACE_HEADER, ctx[0])
        out.setdefault(SPAN_HEADER, ctx[1])
    return out


def extract_trace(headers) -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) from request headers, case-insensitively
    (the server half).  A trace id without a span id is continued with
    an empty parent — the upstream did not tell us which span sent it."""
    tid = sid = None
    for k in headers.keys():
        lk = k.lower()
        if lk == "x-trace-id":
            tid = str(headers[k])
        elif lk == "x-span-id":
            sid = str(headers[k])
    if not tid:
        return None
    return (tid, sid or "")


# ---- read side -----------------------------------------------------------

def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """Every recorded span of one trace, in completion order."""
    with _LOCK:
        return list(_TRACES.get(trace_id, ()))


def span_tree(trace_id: str) -> List[Dict[str, Any]]:
    """The trace's spans nested parent->children (roots returned; a span
    whose parent was sent by a remote upstream roots locally)."""
    spans = get_trace(trace_id)
    nodes = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots: List[Dict[str, Any]] = []
    # completion order ≠ start order: children finish before parents, so
    # sort siblings by start time for a readable tree
    for s in sorted(nodes.values(), key=lambda r: r["t_start"]):
        parent = nodes.get(s["parent_id"]) if s["parent_id"] else None
        if parent is not None:
            parent["children"].append(s)
        else:
            roots.append(s)
    return roots


def recent_spans(n: Optional[int] = None) -> List[Dict[str, Any]]:
    with _LOCK:
        out = list(_SPANS)
    return out if n is None else out[-n:]


def clear_spans() -> None:
    with _LOCK:
        _SPANS.clear()
        _TRACES.clear()
