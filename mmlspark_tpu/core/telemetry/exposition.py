"""Render the registry for consumers: Prometheus text, JSON snapshots,
and the ascii span-tree / latency tables behind `tools/obs_report.py`.

Prometheus exposition convention (text format 0.0.4): dotted internal
names (`serving.request.latency`) sanitize to underscore names
(`serving_request_latency`); histograms expose CUMULATIVE
`_bucket{le="..."}` series ending in `le="+Inf"`, plus `_sum`/`_count`.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry
from . import spans as _spans

__all__ = ["render_prometheus", "export_snapshot", "format_span_tree",
           "format_latency_table", "sanitize_name"]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(labels: Tuple[Tuple[str, str], ...],
                extra: Optional[Tuple[Tuple[str, str], ...]] = None) -> str:
    items = list(labels) + list(extra or ())
    if not items:
        return ""
    body = ",".join(f'{sanitize_name(k)}="{_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def render_prometheus(registry: MetricsRegistry = REGISTRY) -> str:
    """The full registry in Prometheus text format (what `/metrics`
    serves)."""
    lines: List[str] = []
    for name, val in sorted(registry.counter_values().items()):
        pn = sanitize_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {val}")
    for name, val in sorted(registry.gauge_values().items()):
        pn = sanitize_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt_value(val)}")
    hists = registry.histograms()
    typed = set()
    for (name, labels), h in sorted(hists.items(),
                                    key=lambda kv: (kv[0][0], kv[0][1])):
        pn = sanitize_name(name)
        if pn not in typed:
            lines.append(f"# TYPE {pn} histogram")
            typed.add(pn)
        snap = h.snapshot()
        for le, cum in snap["buckets"]:
            lines.append(
                f"{pn}_bucket"
                f"{_fmt_labels(labels, (('le', _fmt_value(le)),))} {cum}")
        lines.append(f"{pn}_sum{_fmt_labels(labels)} "
                     f"{_fmt_value(snap['sum'])}")
        lines.append(f"{pn}_count{_fmt_labels(labels)} {snap['count']}")
    return "\n".join(lines) + "\n"


def _hist_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    return name + "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"


def export_snapshot(registry: MetricsRegistry = REGISTRY,
                    include_spans: bool = True) -> Dict[str, Any]:
    """One JSON-serializable dict of everything the process has
    observed — counters, gauges, histogram snapshots (keyed
    `name` or `name{k="v"}`), and (optionally) the recent-span ring.
    `bench.py` and `tools/chaos_soak.py` report through this; saved to a
    file it is what `tools/obs_report.py` renders."""
    hists: Dict[str, Any] = {}
    for (name, labels), h in registry.histograms().items():
        snap = h.snapshot()
        snap["buckets"] = [
            ["+Inf" if le == math.inf else le, cum]
            for le, cum in snap["buckets"]
        ]
        hists[_hist_key(name, labels)] = snap
    out: Dict[str, Any] = {
        "counters": registry.counter_values(),
        "gauges": registry.gauge_values(),
        "histograms": hists,
    }
    if include_spans:
        out["spans"] = _spans.recent_spans()
    return out


# ---- obs_report renderers ------------------------------------------------

def format_span_tree(roots: List[Dict[str, Any]], indent: str = "") -> str:
    """Ascii tree of nested span dicts (the `span_tree()` shape)."""
    lines: List[str] = []
    for i, node in enumerate(roots):
        last = i == len(roots) - 1
        branch = "└─ " if last else "├─ "
        attrs = node.get("attrs") or {}
        extra = "".join(f" {k}={v}" for k, v in sorted(attrs.items()))
        err = f" !{node['error']}" if node.get("error") else ""
        lines.append(f"{indent}{branch}{node['name']} "
                     f"[{node['wall_s'] * 1e3:.2f} ms]{err}{extra}")
        child_indent = indent + ("   " if last else "│  ")
        children = node.get("children") or []
        if children:
            lines.append(format_span_tree(children, child_indent))
    return "\n".join(lines)


def format_latency_table(histograms: Dict[str, Any]) -> str:
    """p50/p95/p99 table from export_snapshot()['histograms']."""
    rows = [("histogram", "count", "p50", "p95", "p99")]
    for key in sorted(histograms):
        snap = histograms[key]

        def fmt(v: Optional[float]) -> str:
            return "-" if v is None else f"{v:.6g}"

        rows.append((key, str(snap["count"]), fmt(snap.get("p50")),
                     fmt(snap.get("p95")), fmt(snap.get("p99"))))
    widths = [max(len(r[c]) for r in rows) for c in range(5)]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(cell.ljust(w) for cell, w in zip(r, widths))
                     .rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
