"""Render the registry for consumers: Prometheus text, JSON snapshots,
and the ascii span-tree / latency tables behind `tools/obs_report.py`.

Prometheus exposition convention (text format 0.0.4): dotted internal
names (`serving.request.latency`) sanitize to underscore names
(`serving_request_latency`); histograms expose CUMULATIVE
`_bucket{le="..."}` series ending in `le="+Inf"`, plus `_sum`/`_count`.
"""
from __future__ import annotations

import json
import math
import os
import re
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .metrics import REGISTRY, MetricsRegistry
from . import goodput as _goodput
from . import spans as _spans
from . import timeseries as _timeseries

__all__ = ["render_prometheus", "export_snapshot", "render_chrome_trace",
           "format_span_tree", "format_latency_table", "sanitize_name"]

# process uptime baseline: first telemetry import ≈ process start for
# every consumer that records anything
_T0_MONOTONIC = time.monotonic()

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name: str) -> str:
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _fmt_labels(labels: Tuple[Tuple[str, str], ...],
                extra: Optional[Tuple[Tuple[str, str], ...]] = None) -> str:
    items = list(labels) + list(extra or ())
    if not items:
        return ""
    body = ",".join(f'{sanitize_name(k)}="{_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def render_prometheus(registry: MetricsRegistry = REGISTRY) -> str:
    """The full registry in Prometheus text format (what `/metrics`
    serves)."""
    lines: List[str] = []
    for name, val in sorted(registry.counter_values().items()):
        pn = sanitize_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {val}")
    for name, val in sorted(registry.gauge_values().items()):
        pn = sanitize_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt_value(val)}")
    hists = registry.histograms()
    typed = set()
    for (name, labels), h in sorted(hists.items(),
                                    key=lambda kv: (kv[0][0], kv[0][1])):
        pn = sanitize_name(name)
        if pn not in typed:
            lines.append(f"# TYPE {pn} histogram")
            typed.add(pn)
        snap = h.snapshot()
        for le, cum in snap["buckets"]:
            lines.append(
                f"{pn}_bucket"
                f"{_fmt_labels(labels, (('le', _fmt_value(le)),))} {cum}")
        lines.append(f"{pn}_sum{_fmt_labels(labels)} "
                     f"{_fmt_value(snap['sum'])}")
        lines.append(f"{pn}_count{_fmt_labels(labels)} {snap['count']}")
    return "\n".join(lines) + "\n"


def _hist_key(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return name
    return name + "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"


def _json_safe(v: Any) -> Any:
    """`v` if json can carry it, else its repr() — span attrs are
    free-form and a stray ndarray/dtype must degrade to a string, not
    crash a /metrics-adjacent dump."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError, OverflowError):
        return repr(v)


def _safe_span(rec: Dict[str, Any]) -> Dict[str, Any]:
    attrs = rec.get("attrs")
    if not attrs:
        return rec
    return dict(rec, attrs={k: _json_safe(v) for k, v in attrs.items()})


def _snapshot_meta(timestamp: Optional[str]) -> Dict[str, Any]:
    """Self-describing header for saved snapshots.  Backend facts are
    reported only when jax is ALREADY imported — a /metrics-adjacent
    dump must never be the thing that drags jax (and a device grab) into
    the process."""
    meta: Dict[str, Any] = {
        "timestamp": timestamp,
        "pid": os.getpid(),
        "uptime_s": round(time.monotonic() - _T0_MONOTONIC, 3),
        "backend": None,
        "device_count": None,
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            meta["backend"] = jax.default_backend()
            meta["device_count"] = jax.device_count()
        except Exception:
            pass
    return meta


def export_snapshot(registry: MetricsRegistry = REGISTRY,
                    include_spans: bool = True,
                    timestamp: Optional[str] = None) -> Dict[str, Any]:
    """One JSON-serializable dict of everything the process has
    observed — a `meta` header (caller-supplied timestamp, pid, jax
    backend + device count when jax is loaded, process uptime),
    counters, gauges, histogram snapshots (keyed `name` or
    `name{k="v"}`), and (optionally) the recent-span ring with
    non-serializable attrs degraded to repr().  `bench.py` and
    `tools/chaos_soak.py` report through this; saved to a file it is
    what `tools/obs_report.py` renders."""
    hists: Dict[str, Any] = {}
    for (name, labels), h in registry.histograms().items():
        snap = h.snapshot()
        snap["buckets"] = [
            ["+Inf" if le == math.inf else le, cum]
            for le, cum in snap["buckets"]
        ]
        hists[_hist_key(name, labels)] = snap
    out: Dict[str, Any] = {
        "meta": _snapshot_meta(timestamp),
        "counters": registry.counter_values(),
        "gauges": registry.gauge_values(),
        "histograms": hists,
    }
    if include_spans:
        out["spans"] = [_safe_span(r) for r in _spans.recent_spans()]
    # the goodput plane (PR 20): recent history + per-step timelines,
    # only when the process actually produced any — idle servers keep
    # the legacy snapshot shape byte-for-byte
    if registry is REGISTRY:
        ts = _timeseries.STORE.export()
        if ts["series"]:
            out["timeseries"] = ts
        gp = _goodput.LEDGER.export()
        if gp["steps"] or gp["summary"]["lost"] or gp["summary"]["productive_s"]:
            out["goodput"] = gp
    return out


def render_chrome_trace(span_records: Optional[Iterable[Dict[str, Any]]]
                        = None) -> Dict[str, Any]:
    """The span ring as Chrome/Perfetto trace-event JSON — load the
    dump in ui.perfetto.dev or chrome://tracing.

    Each span becomes a `ph:"X"` complete event: ts/dur in microseconds
    (trace-event convention), pid = this process, tid = the thread that
    recorded the span, and trace/span/parent ids + attrs under `args` so
    the causal tree survives into the viewer.  Served at `GET
    /trace.json`; written by `tools/obs_report.py --chrome-out`."""
    if span_records is None:
        span_records = _spans.recent_spans()
    pid = os.getpid()
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": f"mmlspark_tpu[{pid}]"},
    }]
    for rec in span_records:
        name = str(rec.get("name", "?"))
        args: Dict[str, Any] = {
            "trace_id": rec.get("trace_id"),
            "span_id": rec.get("span_id"),
            "parent_id": rec.get("parent_id"),
        }
        for k, v in (rec.get("attrs") or {}).items():
            args[k] = _json_safe(v)
        if rec.get("error"):
            args["error"] = rec["error"]
        events.append({
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": round(float(rec.get("t_start", 0.0)) * 1e6, 3),
            "dur": round(max(0.0, float(rec.get("wall_s", 0.0))) * 1e6, 3),
            "pid": pid,
            "tid": int(rec.get("tid", 0)),
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---- obs_report renderers ------------------------------------------------

def format_span_tree(roots: List[Dict[str, Any]], indent: str = "") -> str:
    """Ascii tree of nested span dicts (the `span_tree()` shape)."""
    lines: List[str] = []
    for i, node in enumerate(roots):
        last = i == len(roots) - 1
        branch = "└─ " if last else "├─ "
        attrs = node.get("attrs") or {}
        extra = "".join(f" {k}={v}" for k, v in sorted(attrs.items()))
        err = f" !{node['error']}" if node.get("error") else ""
        lines.append(f"{indent}{branch}{node['name']} "
                     f"[{node['wall_s'] * 1e3:.2f} ms]{err}{extra}")
        child_indent = indent + ("   " if last else "│  ")
        children = node.get("children") or []
        if children:
            lines.append(format_span_tree(children, child_indent))
    return "\n".join(lines)


def format_latency_table(histograms: Dict[str, Any]) -> str:
    """p50/p95/p99 table from export_snapshot()['histograms']."""
    rows = [("histogram", "count", "p50", "p95", "p99")]
    for key in sorted(histograms):
        snap = histograms[key]

        def fmt(v: Optional[float]) -> str:
            return "-" if v is None else f"{v:.6g}"

        rows.append((key, str(snap["count"]), fmt(snap.get("p50")),
                     fmt(snap.get("p95")), fmt(snap.get("p99"))))
    widths = [max(len(r[c]) for r in rows) for c in range(5)]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(cell.ljust(w) for cell, w in zip(r, widths))
                     .rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
