"""Metric primitives behind one process-wide registry.

Three instrument kinds, Prometheus-shaped (the exposition convention):

* **counters** — the PR-4 event ledger (`incr("serving.shed")`),
  monotonic ints.  Kept as a plain dict under one lock: `incr` is called
  from every fault/retry/shed path and must stay a few hundred ns.
* **gauges** — last-written values (queue depths, overlap fractions,
  examples/sec).  `gauge(name).set(v)` / `.inc()`.
* **histograms** — fixed log-spaced buckets, LOCK-STRIPED: each
  observing thread hashes onto one of `_STRIPES` independent
  (lock, counts, sum) shards so the serving hot path never serializes
  on a single histogram lock; snapshots merge the stripes.

Naming convention: ``layer.component.metric`` (e.g.
``serving.request.latency``, ``io.feed.transfer.bytes``).  Every STATIC
name instrumented anywhere in the tree must appear in
``DECLARED_METRICS`` below — `tools/ci.py metrics-lint` greps call sites
and fails on undeclared literals, so a typo'd metric name cannot
silently record into a parallel series nobody scrapes.  Dynamic
per-entity suffixes (``faults.injected.<point>``,
``circuit.open.<host>``) are valid when their PREFIX is declared.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["DECLARED_METRICS", "is_declared", "Gauge", "Histogram",
           "MetricsRegistry", "REGISTRY", "default_buckets",
           "BYTE_BUCKETS", "FILL_BUCKETS", "BUCKET_FAMILIES",
           "HISTOGRAM_FAMILY", "buckets_for"]

# ---------------------------------------------------------------------------
# The declared-name table: every static metric/counter name in the tree.
# tools/ci.py `metrics-lint` enforces that instrumented literals resolve
# here (exact match, or prefix match for per-entity families).
# ---------------------------------------------------------------------------
DECLARED_METRICS: Dict[str, str] = {
    # -- counters (telemetry.incr): the resilience event ledger (PR 4)
    "serving.shed": "counter",
    "serving.deadline_expired": "counter",
    "batcher.shed": "counter",
    "batcher.deadline_expired": "counter",
    "feed.transfer_retry": "counter",
    "feed.degraded": "counter",
    # -- counters: the sharded direct-to-chip path (io/shard_put.py, PR 14)
    "feed.shard_retry": "counter",
    "feed.shard_degraded": "counter",
    "io.feed.shard.puts": "counter",
    "io.feed.shard.fallback": "counter",
    "io.feed.shard.compressed_groups": "counter",
    "circuit.open": "counter",            # + .<breaker-name> variants
    "circuit.closed": "counter",
    "circuit.half_open_probe": "counter",
    "faults.injected": "counter",         # + .<fault-point> variants
    "training.autosave": "counter",
    "training.resume": "counter",
    # -- counters: training reliability ladder (models/guard.py, PR 10)
    "training.anomaly": "counter",        # + .<kind> variants
    "training.quarantine": "counter",     # + .skip variant (replay skips)
    "training.rollback": "counter",
    "training.abort": "counter",
    "training.hang": "counter",
    "checkpoint.corrupt": "counter",
    "checkpoint.fallback": "counter",
    "checkpoint.quarantine": "counter",
    "checkpoint.write_failed": "counter",
    "io.pipeline.items": "counter",       # + .<stage> variants
    # -- counters: the graftflow runtime ledger (core/flow.py, PR 12)
    "flow.items": "counter",              # + .<stage> variants
    "flow.shed": "counter",               # + .<stage> variants
    "flow.expired": "counter",            # + .<stage> variants
    # registered Stage subclasses declare their exact rows (G405)
    "flow.shed.admission": "counter",
    "flow.expired.admission": "counter",
    "flow.shed.h2d": "counter",
    "flow.expired.h2d": "counter",
    "flow.shed.prefill": "counter",
    "flow.expired.prefill": "counter",
    "xla.compile.count": "counter",       # every observed XLA compile
    "xla.compile.hot_path": "counter",    # + .<fn> variants: steady-state
    # -- counters: fleet gateway event ledger (serving/fleet.py, PR 9)
    "serving.fleet.retry": "counter",
    "serving.fleet.eject": "counter",
    "serving.fleet.reinstate": "counter",
    "serving.fleet.no_replica": "counter",
    "serving.fleet.deadline_expired": "counter",
    "serving.fleet.rollback": "counter",
    "serving.fleet.promote": "counter",
    # -- counters: federated telemetry plane (core/telemetry/fleet.py, PR 15)
    "fleet.pull": "counter",              # one per completed federated pull
    "fleet.pull_failed": "counter",       # + .<replica> variants
    "fleet.incident": "counter",          # flight-recorder bundles written
    "slo.alert.pending": "counter",       # + .<slo> variants
    "slo.alert.firing": "counter",        # + .<slo> variants
    "slo.alert.resolved": "counter",      # + .<slo> variants
    "autoscale.up": "counter",
    "autoscale.down": "counter",
    # -- counters: elastic multi-host runtime (parallel/distributed.py, PR 19)
    "dist.rendezvous.attempt": "counter",   # one per join attempt
    "dist.rendezvous.retry": "counter",     # backed-off re-attempts
    "dist.rendezvous.failed": "counter",    # deadline/budget exhausted
    "dist.heartbeat.missed": "counter",     # dropped beats (injected/lost)
    "dist.host.lost": "counter",            # + .<host> variants
    "dist.membership.update": "counter",    # published epoch advances
    "dist.membership.stale": "counter",     # rejected stale epochs
    "dist.barrier.timeout": "counter",
    "dist.collective.overrun": "counter",   # hang-budget deadline fired
    # -- counters: goodput plane (core/telemetry/timeseries.py+goodput.py)
    "timeseries.samples": "counter",        # one per TimeSeriesStore sweep
    "training.straggler": "counter",        # + .<host> variants (merge side)
    # -- histograms
    "serving.request.latency": "histogram",
    "serving.batch.fill": "histogram",
    "serving.batcher.batch_fill": "histogram",
    "io.feed.transfer.latency": "histogram",
    "io.feed.transfer.bytes": "histogram",
    "io.feed.shard.latency": "histogram",   # one observation per shard put
    "io.feed.shard.bytes": "histogram",
    "io.pipeline.stage.latency": "histogram",   # labeled {stage=...}
    "flow.stage.latency": "histogram",          # labeled {stage=...}
    "io.http.request.latency": "histogram",
    "models.training.step_latency": "histogram",
    "checkpoint.verify.latency": "histogram",
    "xla.compile.latency": "histogram",
    "serving.fleet.request.latency": "histogram",   # gateway e2e, labeled
    "serving.fleet.replica.latency": "histogram",   # labeled {replica=...}
    "fleet.scrape.latency": "histogram",    # one full federated pull+merge
    "dist.rendezvous.latency": "histogram",  # join time, per host
    # -- gauges
    "serving.queue.depth": "gauge",
    "serving.batcher.queue_depth": "gauge",
    "io.feed.degraded_engines": "gauge",
    "io.feed.overlap_frac": "gauge",
    "io.feed.stall_s": "gauge",
    "io.feed.queue.depth": "gauge",
    "io.feed.shard.concurrency": "gauge",   # pool in-flight high-water
    "io.feed.shard.wire_ratio": "gauge",    # raw/sent on the RLE wire
    "io.feed.shard.queue.depth": "gauge",   # transfer-pool task backlog
    "io.pipeline.queue.depth": "gauge",   # + .<stage> variants
    "flow.queue.depth": "gauge",          # + .<stage> variants
    "flow.queue.depth.admission": "gauge",
    "flow.queue.depth.h2d": "gauge",
    "flow.queue.depth.prefill": "gauge",
    "core.batching.queue.depth": "gauge",
    "models.training.examples_per_sec": "gauge",
    "training.guard.lr_scale": "gauge",
    "device.hbm.bytes_in_use": "gauge",
    "device.hbm.peak_bytes": "gauge",
    "device.live_buffer_count": "gauge",
    "serving.fleet.replicas": "gauge",
    "serving.fleet.healthy": "gauge",
    "fleet.pull.replicas": "gauge",       # replicas reached by last pull
    "slo.burn_rate": "gauge",             # + .<slo> variants
    "autoscale.target_replicas": "gauge",
    "dist.membership.epoch": "gauge",     # current membership epoch
    "dist.membership.hosts": "gauge",     # live hosts in the view
    # -- gauges: goodput plane (core/telemetry/goodput.py, PR 20)
    "training.goodput.frac": "gauge",         # productive / wall, whole run
    "training.goodput.window_frac": "gauge",  # same over the last K steps
    "training.goodput.lost_s": "gauge",       # + .<kind> variants
    "training.straggler.ratio": "gauge",      # p_max/p_median at detection
}


def is_declared(name: str) -> bool:
    """Exact member of the table, or a dynamic per-entity child of one
    (``faults.injected.feed.device_put`` under ``faults.injected``)."""
    if name in DECLARED_METRICS:
        return True
    return any(name.startswith(d + ".") for d in DECLARED_METRICS)


# half-decade log spacing, 1 µs .. 1000 s: one default ladder covers
# everything timed in seconds, from a coalesced device_put to a cold
# XLA compile inside a serving tick
def default_buckets() -> Tuple[float, ...]:
    return tuple(10.0 ** (-6 + i / 2.0) for i in range(19))


# power-of-4 spacing, 64 B .. 1 GiB: the transfer-size ladder
BYTE_BUCKETS: Tuple[float, ...] = tuple(float(64 * 4 ** i) for i in range(13))

# linear 0.05 .. 1.0: the fill-fraction ladder (batch occupancy is a
# ratio, not a latency — a log ladder wastes 15 of 19 edges above 1.0)
FILL_BUCKETS: Tuple[float, ...] = tuple(i / 20.0 for i in range(1, 21))

# ---------------------------------------------------------------------------
# Named bucket families.  Every DECLARED histogram must resolve to one of
# these ladders (graftlint M003): fleet-level federation merges replica
# histograms bucket-by-bucket, which is only exact when every replica —
# and every process version in a mixed rollout — shares identical `le`
# edges.  Pinning the ladder at declaration makes edge drift a lint
# error instead of a silently-wrong merged p99.
# ---------------------------------------------------------------------------
BUCKET_FAMILIES: Dict[str, Tuple[float, ...]] = {
    "latency": tuple(10.0 ** (-6 + i / 2.0) for i in range(19)),
    "bytes": BYTE_BUCKETS,
    "fill": FILL_BUCKETS,
}

# declared histogram name -> family key in BUCKET_FAMILIES
HISTOGRAM_FAMILY: Dict[str, str] = {
    "serving.request.latency": "latency",
    "serving.batch.fill": "fill",
    "serving.batcher.batch_fill": "fill",
    "io.feed.transfer.latency": "latency",
    "io.feed.transfer.bytes": "bytes",
    "io.feed.shard.latency": "latency",
    "io.feed.shard.bytes": "bytes",
    "io.pipeline.stage.latency": "latency",
    "flow.stage.latency": "latency",
    "io.http.request.latency": "latency",
    "models.training.step_latency": "latency",
    "checkpoint.verify.latency": "latency",
    "xla.compile.latency": "latency",
    "serving.fleet.request.latency": "latency",
    "serving.fleet.replica.latency": "latency",
    "fleet.scrape.latency": "latency",
    "dist.rendezvous.latency": "latency",
}


def buckets_for(name: str) -> Optional[Tuple[float, ...]]:
    """The family ladder for a declared histogram name (exact or
    per-entity child), or None when the name carries no family."""
    fam = HISTOGRAM_FAMILY.get(name)
    if fam is None:
        for decl, f in HISTOGRAM_FAMILY.items():
            if name.startswith(decl + "."):
                fam = f
                break
    return BUCKET_FAMILIES[fam] if fam is not None else None


_STRIPES = 8


class Gauge:
    """Last-written value; `inc`/`dec` for up-down counts."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0  #: guarded-by self._lock

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _Stripe:
    __slots__ = ("lock", "counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.lock = threading.Lock()
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0


class Histogram:
    """Fixed-boundary histogram, lock-striped across observer threads.

    `boundaries` are the bucket UPPER edges (ascending); observations
    above the last edge land in the implicit +Inf bucket.  An
    observation exactly ON an edge counts into that edge's bucket
    (Prometheus `le` semantics — bucket i holds v <= boundaries[i]).
    """

    def __init__(self, name: str,
                 boundaries: Optional[Sequence[float]] = None):
        self.name = name
        bs = tuple(boundaries) if boundaries is not None else default_buckets()
        if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"histogram boundaries must be strictly "
                             f"ascending, got {bs}")
        self.boundaries: Tuple[float, ...] = bs
        self._stripes = [_Stripe(len(bs) + 1) for _ in range(_STRIPES)]

    def observe(self, value: float) -> None:
        # le semantics: first boundary >= value (bisect_left: an exact
        # edge hit stays in that edge's bucket)
        i = bisect.bisect_left(self.boundaries, value)
        s = self._stripes[threading.get_ident() % _STRIPES]
        with s.lock:
            s.counts[i] += 1
            s.sum += value
            s.count += 1

    # ---- read side -----------------------------------------------------
    def _merged(self) -> Tuple[List[int], float, int]:
        counts = [0] * (len(self.boundaries) + 1)
        total_sum, total_n = 0.0, 0
        for s in self._stripes:
            with s.lock:
                for i, c in enumerate(s.counts):
                    counts[i] += c
                total_sum += s.sum
                total_n += s.count
        return counts, total_sum, total_n

    def percentile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile (q in [0, 1]); None when empty.
        Values in the +Inf bucket report the last finite edge — a
        histogram quantile can never resolve beyond its ladder."""
        counts, _s, n = self._merged()
        if n == 0:
            return None
        target = q * n
        cum = 0.0
        for i, c in enumerate(counts):
            cum += c
            if cum >= target and c > 0:
                if i >= len(self.boundaries):
                    return self.boundaries[-1]
                lo = self.boundaries[i - 1] if i > 0 else 0.0
                hi = self.boundaries[i]
                frac = (target - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.boundaries[-1]

    def snapshot(self) -> Dict[str, object]:
        counts, total_sum, n = self._merged()
        cum, buckets = 0, []
        for i, le in enumerate(self.boundaries):
            cum += counts[i]
            buckets.append((le, cum))
        buckets.append((float("inf"), n))
        return {
            "count": n,
            "sum": total_sum,
            "buckets": buckets,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """One process-wide home for every instrument.

    Counters keep the exact PR-4 dict semantics (incr / counters /
    reset_counters) so the existing chaos assertions hold; gauges and
    histograms are create-on-first-touch keyed by (name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}  #: guarded-by self._lock
        self._gauges: Dict[str, Gauge] = {}  #: guarded-by self._lock
        #: guarded-by self._lock
        self._hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                          Histogram] = {}
        # the bucket ladder is fixed per NAME: every labeled child of
        # one histogram family must be mergeable/comparable
        self._hist_buckets: Dict[str, Tuple[float, ...]] = {}  #: guarded-by self._lock

    # ---- counters ------------------------------------------------------
    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def counter_values(self, prefix: Optional[str] = None) -> Dict[str, int]:
        with self._lock:
            if prefix is None:
                return dict(self._counters)
            return {k: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def reset_counters(self, prefix: Optional[str] = None) -> None:
        with self._lock:
            if prefix is None:
                self._counters.clear()
            else:
                for k in [k for k in self._counters if k.startswith(prefix)]:
                    del self._counters[k]

    # ---- gauges --------------------------------------------------------
    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def gauge_values(self) -> Dict[str, float]:
        with self._lock:
            gauges = list(self._gauges.values())
        return {g.name: g.value for g in gauges}

    # ---- histograms ----------------------------------------------------
    def histogram(self, name: str,
                  boundaries: Optional[Sequence[float]] = None,
                  **labels: str) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                bs = self._hist_buckets.get(name)
                if bs is None:
                    fam = buckets_for(name)
                    if fam is not None:
                        # declared family names are pinned to their
                        # ladder: an explicit disagreeing `boundaries`
                        # would make fleet merges inexact (M003)
                        if (boundaries is not None
                                and tuple(boundaries) != fam):
                            raise ValueError(
                                f"histogram {name!r} is declared with a "
                                f"bucket family; explicit boundaries "
                                f"must match it")
                        bs = fam
                    else:
                        bs = (tuple(boundaries) if boundaries is not None
                              else default_buckets())
                    self._hist_buckets[name] = bs
                h = self._hists[key] = Histogram(name, bs)
            return h

    def histograms(self) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                 Histogram]:
        with self._lock:
            return dict(self._hists)

    def reset_all(self) -> None:
        """Tests only: counters, gauges, and histograms back to empty."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._hist_buckets.clear()


REGISTRY = MetricsRegistry()
