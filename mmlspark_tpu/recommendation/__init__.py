"""Recommendation: SAR collaborative filtering + ranking evaluation/tuning.

Reference: core recommendation/ (~1.3k LoC, SAR.scala:36-260, SARModel.scala,
RecommendationIndexer.scala, RankingAdapter.scala, RankingEvaluator.scala,
RankingTrainValidationSplit.scala).
"""
from .indexer import RecommendationIndexer, RecommendationIndexerModel
from .ranking import (
    RankingAdapter,
    RankingAdapterModel,
    RankingEvaluator,
    map_at_k,
    ndcg_at_k,
    precision_at_k,
    recall_at_k,
)
from .sar import SAR, SARModel
from .tvs import (
    RankingTrainValidationSplit,
    RankingTrainValidationSplitModel,
    per_user_split,
)

__all__ = [
    "SAR",
    "SARModel",
    "RecommendationIndexer",
    "RecommendationIndexerModel",
    "RankingAdapter",
    "RankingAdapterModel",
    "RankingEvaluator",
    "RankingTrainValidationSplit",
    "RankingTrainValidationSplitModel",
    "per_user_split",
    "ndcg_at_k",
    "map_at_k",
    "precision_at_k",
    "recall_at_k",
]
