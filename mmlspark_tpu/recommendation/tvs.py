"""RankingTrainValidationSplit: per-user holdout + param-grid search.

Reference: core recommendation/RankingTrainValidationSplit.scala (354 LoC) —
stratified-by-user train/validation split, sweep a param grid over the
wrapped recommender, keep the best by RankingEvaluator metric.
"""
from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.registry import register_stage
from ..core.schema import Table
from .ranking import RankingAdapter, RankingEvaluator

__all__ = ["RankingTrainValidationSplit", "RankingTrainValidationSplitModel"]


def per_user_split(table: Table, user_col: str, train_ratio: float,
                   seed: int = 0):
    """Stratified split: every user keeps ~train_ratio of their events in
    train (min 1), the rest go to validation."""
    users = np.asarray(table[user_col])
    rng = np.random.default_rng(seed)
    train_mask = np.zeros(len(table), bool)
    for u in np.unique(users):
        idx = np.nonzero(users == u)[0]
        perm = rng.permutation(idx)
        n_train = max(int(round(len(idx) * train_ratio)), 1)
        train_mask[perm[:n_train]] = True
    return table.filter(train_mask), table.filter(~train_mask)


@register_stage
class RankingTrainValidationSplit(Estimator):
    estimator = ComplexParam("recommender Estimator to tune")
    param_grid = ComplexParam("list of param dicts to sweep", default=None)
    evaluator = ComplexParam("RankingEvaluator", default=None)
    train_ratio = Param("per-user train fraction", default=0.75,
                        converter=TypeConverters.to_float)
    user_col = Param("user index column", default="user")
    item_col = Param("item index column", default="item")
    rating_col = Param("rating column", default="rating")
    seed = Param("split seed", default=0, converter=TypeConverters.to_int)

    def _fit(self, table: Table) -> "RankingTrainValidationSplitModel":
        evaluator: RankingEvaluator = (
            self.get_or_default("evaluator") or RankingEvaluator()
        )
        grid: List[Dict[str, Any]] = self.get_or_default("param_grid") or [{}]
        train, valid = per_user_split(
            table, self.user_col, float(self.train_ratio), int(self.seed)
        )
        best_metric, best_model, metrics = None, None, []
        larger_better = evaluator.is_larger_better()
        for params in grid:
            est = self.estimator.copy(params)
            adapter = RankingAdapter(
                recommender=est, k=evaluator.k,
                user_col=self.user_col, item_col=self.item_col,
                rating_col=self.rating_col,
            )
            adapter_model = adapter.fit(train)
            ranked = adapter_model.transform(valid)
            m = evaluator.evaluate(ranked)
            metrics.append(m)
            better = (
                best_metric is None
                or (m > best_metric if larger_better else m < best_metric)
            )
            if better:
                best_metric = m
                best_model = adapter_model.recommender_model
        return RankingTrainValidationSplitModel(
            best_model=best_model,
            validation_metrics=metrics,
        )


@register_stage
class RankingTrainValidationSplitModel(Model):
    best_model = ComplexParam("winning fitted recommender model")
    validation_metrics = ComplexParam("metric per grid point", default=None)

    def _transform(self, table: Table) -> Table:
        return self.best_model.transform(table)

    def recommend_for_all_users(self, k: int = 10) -> Table:
        return self.best_model.recommend_for_all_users(k)
