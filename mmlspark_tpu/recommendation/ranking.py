"""Ranking evaluation: NDCG/MAP/precision/recall @k + the adapter stage.

Reference: core recommendation/RankingAdapter.scala (wraps a recommender so a
plain Estimator interface yields per-user (recommended, ground-truth) lists)
and RankingEvaluator.scala (SparkML RankingMetrics bridge: ndcgAt, map,
precisionAtk, recallAtK, diversityAtK, maxDiversity).
"""
from __future__ import annotations

from typing import Sequence

import numpy as np

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.registry import register_stage
from ..core.schema import Table

__all__ = ["RankingEvaluator", "RankingAdapter", "RankingAdapterModel",
           "ndcg_at_k", "map_at_k", "precision_at_k", "recall_at_k"]


def _as_set(x) -> set:
    return set(int(v) for v in np.asarray(x).reshape(-1))


def ndcg_at_k(recommended: Sequence[int], relevant: Sequence[int], k: int) -> float:
    rel = _as_set(relevant)
    if not rel:
        return 0.0
    rec = list(recommended)[:k]
    dcg = sum(1.0 / np.log2(i + 2) for i, r in enumerate(rec) if int(r) in rel)
    ideal = sum(1.0 / np.log2(i + 2) for i in range(min(len(rel), k)))
    return float(dcg / ideal) if ideal > 0 else 0.0


def map_at_k(recommended: Sequence[int], relevant: Sequence[int], k: int) -> float:
    rel = _as_set(relevant)
    if not rel:
        return 0.0
    rec = list(recommended)[:k]
    hits, s = 0, 0.0
    for i, r in enumerate(rec):
        if int(r) in rel:
            hits += 1
            s += hits / (i + 1)
    return float(s / min(len(rel), k))


def precision_at_k(recommended, relevant, k: int) -> float:
    rel = _as_set(relevant)
    rec = list(recommended)[:k]
    if not rec:
        return 0.0
    return float(sum(1 for r in rec if int(r) in rel) / k)


def recall_at_k(recommended, relevant, k: int) -> float:
    rel = _as_set(relevant)
    if not rel:
        return 0.0
    rec = list(recommended)[:k]
    return float(sum(1 for r in rec if int(r) in rel) / len(rel))


_METRICS = {
    "ndcgAt": ndcg_at_k,
    "map": map_at_k,
    "precisionAtk": precision_at_k,
    "recallAtK": recall_at_k,
}


class RankingEvaluator:
    """Evaluate a Table of per-user (recommended, ground-truth) item lists.

    Reference: recommendation/RankingEvaluator.scala; metric names kept
    identical for parity.
    """

    def __init__(self, metric_name: str = "ndcgAt", k: int = 10,
                 prediction_col: str = "recommendations",
                 label_col: str = "ground_truth"):
        if metric_name not in _METRICS and metric_name != "diversityAtK":
            raise ValueError(f"unknown metric {metric_name!r}")
        self.metric_name = metric_name
        self.k = int(k)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, table: Table) -> float:
        recs = table[self.prediction_col]
        truth = table[self.label_col]
        if self.metric_name == "diversityAtK":
            shown = set()
            all_items = set()
            for i in range(len(table)):
                shown |= _as_set(list(recs[i])[: self.k])
                all_items |= _as_set(truth[i])
                all_items |= _as_set(recs[i])
            return float(len(shown) / max(len(all_items), 1))
        fn = _METRICS[self.metric_name]
        vals = [fn(recs[i], truth[i], self.k) for i in range(len(table))]
        return float(np.mean(vals)) if vals else 0.0

    def is_larger_better(self) -> bool:
        return True


@register_stage
class RankingAdapter(Estimator):
    """Wrap a recommender Estimator so fit/transform yields per-user
    (recommendations, ground_truth) lists ready for RankingEvaluator.

    Reference: recommendation/RankingAdapter.scala.
    """

    recommender = ComplexParam("the wrapped recommender Estimator")
    k = Param("recommendations per user", default=10,
              converter=TypeConverters.to_int)
    user_col = Param("user index column", default="user")
    item_col = Param("item index column", default="item")
    rating_col = Param("rating column", default="rating")
    min_rating_filter = Param("only items rated >= this count as relevant",
                              default=0, converter=TypeConverters.to_float)

    def _fit(self, table: Table) -> "RankingAdapterModel":
        model = self.recommender.fit(table)
        return RankingAdapterModel(
            recommender_model=model, k=int(self.k),
            user_col=self.user_col, item_col=self.item_col,
            rating_col=self.rating_col,
            min_rating_filter=float(self.min_rating_filter),
        )


@register_stage
class RankingAdapterModel(Model):
    recommender_model = ComplexParam("fitted recommender model")
    k = Param("recommendations per user", default=10,
              converter=TypeConverters.to_int)
    user_col = Param("user index column", default="user")
    item_col = Param("item index column", default="item")
    rating_col = Param("rating column", default="rating")
    min_rating_filter = Param("relevance threshold", default=0.0,
                              converter=TypeConverters.to_float)

    def _transform(self, table: Table) -> Table:
        """Emit one row per user present in `table`: top-k recs + the user's
        observed items (the eval ground truth)."""
        model = self.recommender_model
        recs = model.recommend_for_all_users(int(self.k))
        users = np.asarray(table[self.user_col], np.int64)
        items = np.asarray(table[self.item_col], np.int64)
        ratings = (
            np.asarray(table[self.rating_col], np.float64)
            if self.rating_col in table
            else np.ones(len(table))
        )
        thresh = float(self.min_rating_filter)
        # one sort-and-split pass instead of a per-user scan of all rows
        relevant = ratings >= thresh
        order = np.argsort(users[relevant], kind="stable")
        sorted_users = users[relevant][order]
        sorted_items = items[relevant][order]
        uniq_rel, starts = np.unique(sorted_users, return_index=True)
        truth_map = {
            int(u): sorted_items[s:e]
            for u, s, e in zip(
                uniq_rel, starts, np.append(starts[1:], len(sorted_items))
            )
        }
        uniq = np.unique(users)
        rec_map = {int(u): r for u, r in zip(recs[self.user_col],
                                             recs["recommendations"])}
        out_recs = np.empty(len(uniq), dtype=object)
        out_truth = np.empty(len(uniq), dtype=object)
        for j, u in enumerate(uniq):
            out_truth[j] = truth_map.get(int(u), np.zeros(0, np.int64))
            out_recs[j] = rec_map.get(int(u), np.zeros(0, np.int64))
        return Table({
            self.user_col: uniq,
            "recommendations": out_recs,
            "ground_truth": out_truth,
        })
