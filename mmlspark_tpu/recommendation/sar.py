"""SAR: Smart Adaptive Recommendations (item-item collaborative filtering).

Reference: core recommendation/SAR.scala:36-260 (co-occurrence similarity
jaccard/lift, time-decayed user affinities) and SARModel.scala (178 LoC,
recommend-for-all-users via BLAS gemv over broadcast item factors).

TPU-native redesign: the reference computes co-occurrence with DataFrame
self-joins and scores users with per-row gemv; here the binarized user-item
matrix B lives on device, co-occurrence C = Bᵀ B is ONE MXU matmul, and
recommend-for-all-users is the (users × items) @ (items × items) matmul +
top-k — all jitted, bfloat16-friendly, batch-sharded over the mesh for large
user counts.
"""
from __future__ import annotations

from functools import partial
import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.registry import register_stage
from ..core.schema import Table

__all__ = ["SAR", "SARModel"]


@jax.jit
def _cooccurrence(B):
    return B.T @ B


@jax.jit
def _jaccard(C, counts):
    denom = counts[:, None] + counts[None, :] - C
    return jnp.where(denom > 0, C / jnp.maximum(denom, 1e-12), 0.0)


@jax.jit
def _lift(C, counts):
    denom = counts[:, None] * counts[None, :]
    return jnp.where(denom > 0, C / jnp.maximum(denom, 1e-12), 0.0)


@partial(jax.jit, static_argnames=("k",))
def _topk_unseen(scores, seen, k: int):
    """Mask already-seen items to -inf, take top-k per user."""
    masked = jnp.where(seen > 0, -jnp.inf, scores)
    vals, idx = jax.lax.top_k(masked, k)
    return vals, idx


@register_stage
class SAR(Estimator):
    """Fit time-decayed affinities + item-item similarity.

    Expects integer-indexed user/item columns (use RecommendationIndexer
    first, as the reference pipelines do).
    """

    user_col = Param("user index column", default="user")
    item_col = Param("item index column", default="item")
    rating_col = Param("rating column", default="rating")
    timestamp_col = Param("optional timestamp column (seconds)", default="")
    similarity_function = Param("jaccard|lift|cooccurrence", default="jaccard")
    time_decay_coeff = Param("half-life in days for affinity decay", default=30,
                             converter=TypeConverters.to_int)
    support_threshold = Param("min co-occurrence support", default=4,
                              converter=TypeConverters.to_int)

    def _fit(self, table: Table) -> "SARModel":
        users = np.asarray(table[self.user_col], np.int64)
        items = np.asarray(table[self.item_col], np.int64)
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0
        ratings = (
            np.asarray(table[self.rating_col], np.float32)
            if self.rating_col in table
            else np.ones(len(table), np.float32)
        )

        ts_col = self.timestamp_col
        if ts_col and ts_col in table:
            ts = np.asarray(table[ts_col], np.float64)
            ref = ts.max()
            half_life_s = float(self.time_decay_coeff) * 86400.0
            decay = np.power(2.0, -(ref - ts) / half_life_s).astype(np.float32)
        else:
            decay = np.ones(len(table), np.float32)

        # affinity: sum of decayed ratings per (user, item)
        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (users, items), ratings * decay)

        # item-item co-occurrence on device (one MXU matmul).  Semantics
        # are reference-exact (SAR.scala:185-199, verified against the
        # committed sim_{count,jac,lift}{1,3} fixtures): the support
        # threshold zeroes entries whose RAW co-occurrence is below it —
        # including the diagonal (cooc(i,i) = occ(i)) — while surviving
        # entries divide by the raw counts; the diagonal is kept (seen-item
        # masking, not a zeroed diagonal, is what stops self-recommendation).
        B = jnp.asarray((affinity > 0).astype(np.float32))
        C = _cooccurrence(B)
        counts = jnp.diag(C)  # occ(i): co-occurrence of an item with itself

        fn = self.similarity_function
        if fn == "jaccard":
            S = _jaccard(C, counts)
        elif fn == "lift":
            S = _lift(C, counts)
        elif fn == "cooccurrence":
            S = C
        else:
            raise ValueError(f"unknown similarity_function {fn!r}")
        S = jnp.where(C >= float(self.support_threshold), S, 0.0)

        return SARModel(
            user_affinity=affinity,
            item_similarity=np.asarray(S),
            user_col=self.user_col, item_col=self.item_col,
            rating_col=self.rating_col,
        )


@register_stage
class SARModel(Model):
    """Scores = affinity @ similarity; top-k with seen-item masking.

    Reference: SARModel.scala recommendForAllUsers / transform.
    """

    user_col = Param("user index column", default="user")
    item_col = Param("item index column", default="item")
    rating_col = Param("rating column", default="rating")
    prediction_col = Param("prediction column", default="prediction")
    user_affinity = ComplexParam("(n_users, n_items) affinity matrix")
    item_similarity = ComplexParam("(n_items, n_items) similarity matrix")

    def _scores(self) -> jnp.ndarray:
        A = jnp.asarray(self.user_affinity)
        S = jnp.asarray(self.item_similarity)
        return A @ S

    def recommend_for_all_users(self, k: int = 10) -> Table:
        """Per-user top-k unseen items: Table(user, recommendations, scores)."""
        A = np.asarray(self.user_affinity)
        k = min(int(k), A.shape[1])  # lax.top_k requires k <= item count
        scores = self._scores()
        vals, idx = _topk_unseen(scores, jnp.asarray((A > 0).astype(np.float32)), k)
        vals, idx = np.asarray(vals), np.asarray(idx)
        n_users = A.shape[0]
        recs = np.empty(n_users, dtype=object)
        scs = np.empty(n_users, dtype=object)
        for u in range(n_users):
            good = np.isfinite(vals[u])
            recs[u] = idx[u][good].astype(np.int64)
            scs[u] = vals[u][good].astype(np.float32)
        return Table({
            self.user_col: np.arange(n_users, dtype=np.int64),
            "recommendations": recs,
            "scores": scs,
        })

    def _transform(self, table: Table) -> Table:
        users = np.asarray(table[self.user_col], np.int64)
        items = np.asarray(table[self.item_col], np.int64)
        scores = np.asarray(self._scores())
        n_users, n_items = scores.shape
        ok = (users >= 0) & (users < n_users) & (items >= 0) & (items < n_items)
        out = np.zeros(len(table), np.float32)
        out[ok] = scores[users[ok], items[ok]]
        return table.with_column(self.prediction_col, out)
