"""RecommendationIndexer: string user/item ids -> contiguous indices.

Reference: core recommendation/RecommendationIndexer.scala (user+item
StringIndexer pair with inverse transform for recommendations).
"""
from __future__ import annotations

import numpy as np

from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model
from ..core.registry import register_stage
from ..core.schema import CategoricalMap, Table

__all__ = ["RecommendationIndexer", "RecommendationIndexerModel"]


@register_stage
class RecommendationIndexer(Estimator):
    user_input_col = Param("raw user column", default="customerID")
    user_output_col = Param("indexed user column", default="user")
    item_input_col = Param("raw item column", default="itemID")
    item_output_col = Param("indexed item column", default="item")
    rating_col = Param("rating column (passed through)", default="rating")

    def _fit(self, table: Table) -> "RecommendationIndexerModel":
        users = CategoricalMap(sorted({str(v) for v in table[self.user_input_col]}))
        items = CategoricalMap(sorted({str(v) for v in table[self.item_input_col]}))
        return RecommendationIndexerModel(
            user_map=users, item_map=items,
            user_input_col=self.user_input_col,
            user_output_col=self.user_output_col,
            item_input_col=self.item_input_col,
            item_output_col=self.item_output_col,
        )


@register_stage
class RecommendationIndexerModel(Model):
    user_input_col = Param("raw user column", default="customerID")
    user_output_col = Param("indexed user column", default="user")
    item_input_col = Param("raw item column", default="itemID")
    item_output_col = Param("indexed item column", default="item")
    user_map = ComplexParam("user CategoricalMap")
    item_map = ComplexParam("item CategoricalMap")

    def _transform(self, table: Table) -> Table:
        umap: CategoricalMap = self.user_map
        imap: CategoricalMap = self.item_map
        u = np.array(
            [umap.get_index_option(str(v)) for v in table[self.user_input_col]],
            dtype=object,
        )
        i = np.array(
            [imap.get_index_option(str(v)) for v in table[self.item_input_col]],
            dtype=object,
        )
        keep = np.array([x is not None for x in u], dtype=bool) & np.array(
            [x is not None for x in i], dtype=bool
        )
        out = table.filter(keep)
        out = out.with_column(
            self.user_output_col,
            np.array([x for x in u[keep]], np.int64),
        )
        return out.with_column(
            self.item_output_col,
            np.array([x for x in i[keep]], np.int64),
        )

    def recover_user(self, index: int) -> str:
        return self.user_map.get_level(index)

    def recover_item(self, index: int) -> str:
        return self.item_map.get_level(index)
