"""Compressed-wire codec: RLE-encoded pixels decoded ON DEVICE.

The h2d wall is a bandwidth wall (BENCH_LASTGOOD: 0.058 GB/s), and the
cheapest byte is the one never sent.  Classification pixels are highly
runnable — letterboxed borders, flat backgrounds, uint8 quantization —
so the feed's compressed path ships a byte-level run-length encoding of
each chunk (values + a cumulative-length table) and expands it back into
the raw uint8 buffer on the chip:

  * **Wire format.**  `rle_encode` walks the chunk's raw bytes into
    (value, run) pairs with runs capped at 255 (a worst-case incompres-
    sible buffer costs 5 bytes per byte-run; real image batches measure
    2-20x smaller — `rle_ratio` reports per chunk, and the feed only
    takes this path when the ratio clears `MIN_WIRE_RATIO`).  The wire
    carries `values` (uint8[R]) and the cumulative `ends` table
    (int32[R]); both are padded to a power-of-two run count so the
    on-device decode program caches by (R, N) signature instead of
    recompiling per batch.
  * **XLA decode (every backend).**  `jnp.repeat(values, counts,
    total_repeat_length=N)` — counts recovered on device by differencing
    `ends`.  This is the transparent-fallback rung: it runs anywhere,
    so a backend without Pallas still gets the wire savings.
  * **Pallas page-walk decode (TPU).**  The paged-KV kernel
    (ops/paged_attention.py) proved the pattern: a scalar-prefetched
    table drives each grid step's BlockSpec index map, so every output
    block DMAs exactly the slab it needs.  Here the prefetched table is
    `first_run[p]` — the index of the run containing output position
    p*B, built host-side by one searchsorted over `ends` — and each
    output block walks two adjacent W-run windows of (values, ends)
    whose base indices come straight from that table.  Two windows
    because a B-byte block can span at most B runs starting anywhere
    inside a window: with B == W the pair always covers it.  Selected
    by `rle_kernel_ok()` (TPU backend, or forced via
    MMLSPARK_RLE_KERNEL=1 for interpret-mode tests on CPU).

See docs/performance.md ("Demolishing the h2d wall") and the guide at
/opt/skills/guides/pallas_guide.md for the scalar-prefetch idiom.
"""
from __future__ import annotations

import os
from functools import lru_cache
from typing import Any, Tuple

import numpy as np

__all__ = ["RLEPayload", "rle_encode", "rle_ratio", "rle_kernel_ok",
           "decode_bytes", "decode_host", "MIN_WIRE_RATIO", "RUN_CAP",
           "BLOCK"]

RUN_CAP = 255        # max run length per entry (worst case 5 bytes/run wire)
BLOCK = 128          # output bytes per grid step == runs per window (B == W)
MIN_WIRE_RATIO = 1.5  # feed takes the compressed path only above this


class RLEPayload:
    """One host chunk, RLE-encoded for the wire.

    `values`/`ends` are the padded wire arrays (uint8[R], int32[R], R a
    power of two >= 2*BLOCK); `first_run` is the scalar-prefetch table
    for the Pallas decode; `shape`/`dtype` restore the chunk; `n_pad`
    is the padded decoded byte length (multiple of BLOCK)."""

    __slots__ = ("values", "ends", "first_run", "shape", "dtype",
                 "nbytes_raw", "n_pad")

    def __init__(self, values: np.ndarray, ends: np.ndarray,
                 first_run: np.ndarray, shape: Tuple[int, ...],
                 dtype: np.dtype, nbytes_raw: int, n_pad: int):
        self.values = values
        self.ends = ends
        self.first_run = first_run
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes_raw = int(nbytes_raw)
        self.n_pad = int(n_pad)

    @property
    def wire_nbytes(self) -> int:
        return int(self.values.nbytes + self.ends.nbytes)


def _pow2_at_least(n: int, floor: int) -> int:
    p = floor
    while p < n:
        p <<= 1
    return p


def rle_encode(arr: np.ndarray) -> RLEPayload:
    """Byte-level RLE of `arr`'s raw buffer, runs capped at RUN_CAP.

    Vectorized: change points via one diff over the byte view, then the
    cap splits long runs arithmetically — no Python-per-byte loop."""
    arr = np.ascontiguousarray(arr)
    raw = arr.reshape(-1).view(np.uint8)
    n = raw.size
    if n == 0:
        raise ValueError("cannot RLE-encode an empty array")
    # run boundaries: index i starts a run iff raw[i] != raw[i-1]
    starts = np.flatnonzero(np.concatenate(
        ([True], raw[1:] != raw[:-1])))
    lengths = np.diff(np.concatenate((starts, [n]))).astype(np.int64)
    vals = raw[starts]
    # split runs longer than RUN_CAP into ceil(len/cap) capped pieces
    pieces = -(-lengths // RUN_CAP)
    values = np.repeat(vals, pieces)
    counts = np.full(values.size, RUN_CAP, np.int64)
    # last piece of each run carries the remainder
    last = np.cumsum(pieces) - 1
    rem = lengths - (pieces - 1) * RUN_CAP
    counts[last] = rem
    ends = np.cumsum(counts)
    # pad the decoded length to a BLOCK multiple with one final pad run,
    # then pad the run count to a power of two with zero-length runs so
    # the decode program caches by (R, N) instead of recompiling
    n_pad = -(-n // BLOCK) * BLOCK
    # always append a terminal pad run ending at n_pad: padded output
    # positions must resolve to SOME run, and it also absorbs the
    # BLOCK-rounding slack when n is not a multiple of BLOCK
    ends = np.concatenate((ends, np.array([n_pad], np.int64)))
    values = np.concatenate((values, np.array([0], np.uint8)))
    r_pad = _pow2_at_least(ends.size, 2 * BLOCK)
    ends_p = np.full(r_pad, n_pad, np.int32)
    ends_p[:ends.size] = ends
    vals_p = np.zeros(r_pad, np.uint8)
    vals_p[:values.size] = values
    nb = n_pad // BLOCK
    first_run = np.searchsorted(
        ends_p, np.arange(nb, dtype=np.int64) * BLOCK, side="right"
    ).astype(np.int32)
    return RLEPayload(vals_p, ends_p, first_run, arr.shape, arr.dtype,
                      n, n_pad)


def decode_host(payload: RLEPayload) -> np.ndarray:
    """Host-side decode (the degraded-feed fallback: raw bytes back on
    the host, then a plain put)."""
    counts = np.diff(payload.ends.astype(np.int64), prepend=0)
    raw = np.repeat(payload.values, counts)
    return (raw[:payload.nbytes_raw].view(payload.dtype)
            .reshape(payload.shape))


def rle_ratio(payload: RLEPayload) -> float:
    """Raw bytes per wire byte — the compression the wire would see."""
    return payload.nbytes_raw / max(1, payload.wire_nbytes)


def rle_kernel_ok() -> bool:
    """Route decode through the Pallas page-walk kernel?  TPU only by
    default (the XLA repeat path is faster through CPU interpret mode);
    MMLSPARK_RLE_KERNEL=1 forces it so tier-1 tests exercise the kernel
    in interpret mode, MMLSPARK_NO_RLE_KERNEL wins over both."""
    from .pallas_kernels import pallas_available

    if not pallas_available() or os.environ.get("MMLSPARK_NO_RLE_KERNEL"):
        return False
    if os.environ.get("MMLSPARK_RLE_KERNEL"):
        return True
    import jax

    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# decode programs, cached per (R, N) signature
# ---------------------------------------------------------------------------
@lru_cache(maxsize=64)
def _xla_decode(r: int, n_pad: int):
    import jax
    import jax.numpy as jnp

    def decode(values, ends):
        counts = jnp.diff(ends, prepend=0)
        return jnp.repeat(values, counts, total_repeat_length=n_pad)

    return jax.jit(decode)


@lru_cache(maxsize=64)
def _pallas_decode(r: int, n_pad: int):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .pallas_kernels import _interpret

    w = BLOCK
    nw = r // w          # run windows (r is a pow2 >= 2*BLOCK)
    nb = n_pad // BLOCK  # output blocks

    def kernel(fr_ref, v0_ref, v1_ref, e0_ref, e1_ref, o_ref):
        p = pl.program_id(0)
        w0 = fr_ref[p] // w
        # second window duplicates the first when clamped at the table's
        # edge — mask its contribution instead of double-counting
        dup = (jnp.minimum(w0 + 1, nw - 1) == w0)
        ends = jnp.concatenate([e0_ref[0], e1_ref[0]]).astype(jnp.int32)
        vals = jnp.concatenate([v0_ref[0], v1_ref[0]]).astype(jnp.int32)
        pos = p * BLOCK + jax.lax.broadcasted_iota(
            jnp.int32, (BLOCK, 2 * w), 0)
        lane = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, 2 * w), 1)
        live = (lane < w) | ~dup
        # the run holding each position: count of window ends <= pos
        # (runs before the window all ended by first_run's definition)
        covered = (ends[None, :] <= pos) & live
        local = jnp.sum(covered.astype(jnp.int32), axis=1)  # [BLOCK]
        onehot = (local[:, None] == lane) & live
        o_ref[0] = jnp.sum(
            jnp.where(onehot, vals[None, :], 0), axis=1).astype(jnp.uint8)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # first_run
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, w), lambda p, fr: (fr[p] // w, 0)),
            pl.BlockSpec((1, w),
                         lambda p, fr: (jnp.minimum(fr[p] // w + 1, nw - 1),
                                        0)),
            pl.BlockSpec((1, w), lambda p, fr: (fr[p] // w, 0)),
            pl.BlockSpec((1, w),
                         lambda p, fr: (jnp.minimum(fr[p] // w + 1, nw - 1),
                                        0)),
        ],
        out_specs=pl.BlockSpec((1, BLOCK), lambda p, fr: (p, 0)),
    )

    def decode(first_run, values, ends):
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((nb, BLOCK), jnp.uint8),
            grid_spec=grid_spec,
            interpret=_interpret(),
        )(first_run, values.reshape(nw, w), values.reshape(nw, w),
          ends.reshape(nw, w), ends.reshape(nw, w))
        return out.reshape(n_pad)

    return jax.jit(decode)


def decode_bytes(values: Any, ends: Any, first_run: np.ndarray,
                 n_pad: int, use_pallas: bool) -> Any:
    """values/ends already ON DEVICE -> decoded uint8[n_pad] on device.
    `first_run` stays a host array: it is the scalar-prefetch operand."""
    r = int(values.shape[0])
    if use_pallas:
        return _pallas_decode(r, int(n_pad))(first_run, values, ends)
    return _xla_decode(r, int(n_pad))(values, ends)
