"""Batched image ops: the OpenCV replacement, XLA-native.

Reference: opencv/.../ImageTransformer.scala:27-219 — ResizeImage, CropImage,
ColorFormat (cvtColor), Flip, Blur, Threshold, GaussianKernel applied via
org.opencv Mats per row.  Here every op is a jittable function over a
`[B, H, W, C] float32` batch so the whole preprocessing pipeline fuses into
one XLA program (HBM-bandwidth friendly: one round trip, fused elementwise).
OpenCV convention notes: images arrive BGR uint8 (as Spark image rows do);
gray conversion uses the BT.601 weights OpenCV uses.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "resize",
    "center_crop",
    "crop",
    "flip",
    "color_convert",
    "gaussian_kernel",
    "gaussian_blur",
    "box_blur",
    "threshold",
    "normalize",
    "hwc_to_chw_flat",
    "chw_flat_to_hwc",
]


def resize(batch: jnp.ndarray, height: int, width: int, method: str = "linear") -> jnp.ndarray:
    """Bilinear/nearest resize of [B,H,W,C] (ImageTransformer ResizeImage,
    ImageTransformer.scala:127-146; core/image/ResizeImageTransformer.scala)."""
    b, _, _, c = batch.shape
    return jax.image.resize(batch, (b, height, width, c), method=method)


def crop(batch: jnp.ndarray, x: int, y: int, width: int, height: int) -> jnp.ndarray:
    """Rectangular crop at (x, y) — ImageTransformer CropImage (:148-166)."""
    return batch[:, y : y + height, x : x + width, :]


def center_crop(batch: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    _, h, w, _ = batch.shape
    y = max((h - height) // 2, 0)
    x = max((w - width) // 2, 0)
    return crop(batch, x, y, width, height)


def flip(batch: jnp.ndarray, flip_left_right: bool = True, flip_up_down: bool = False) -> jnp.ndarray:
    """ImageTransformer Flip (:186-199); ImageSetAugmenter uses both."""
    if flip_left_right:
        batch = batch[:, :, ::-1, :]
    if flip_up_down:
        batch = batch[:, ::-1, :, :]
    return batch


# BT.601 luma weights in BGR channel order (OpenCV default layout).
_BGR2GRAY = jnp.array([0.114, 0.587, 0.299])


def color_convert(batch: jnp.ndarray, mode: str) -> jnp.ndarray:
    """bgr2rgb | rgb2bgr | bgr2gray | rgb2gray | gray2bgr — ImageTransformer
    ColorFormat (:168-184)."""
    mode = mode.lower()
    if mode in ("bgr2rgb", "rgb2bgr"):
        return batch[..., ::-1]
    if mode == "bgr2gray":
        return jnp.sum(batch * _BGR2GRAY, axis=-1, keepdims=True)
    if mode == "rgb2gray":
        return jnp.sum(batch * _BGR2GRAY[::-1], axis=-1, keepdims=True)
    if mode in ("gray2bgr", "gray2rgb"):
        return jnp.repeat(batch, 3, axis=-1)
    raise ValueError(f"unknown color mode {mode!r}")


def gaussian_kernel(ksize: int, sigma: float) -> np.ndarray:
    """2-D Gaussian kernel matching cv2.getGaussianKernel semantics
    (ImageTransformer GaussianKernel stage, :201-219)."""
    if sigma <= 0:
        sigma = 0.3 * ((ksize - 1) * 0.5 - 1) + 0.8
    ax = np.arange(ksize, dtype=np.float64) - (ksize - 1) / 2.0
    g = np.exp(-(ax**2) / (2.0 * sigma**2))
    g /= g.sum()
    return np.outer(g, g).astype(np.float32)


def _depthwise_conv2d(batch: jnp.ndarray, kernel2d: jnp.ndarray) -> jnp.ndarray:
    """Same-padded per-channel 2-D convolution on [B,H,W,C]."""
    c = batch.shape[-1]
    k = kernel2d[:, :, None, None]  # HWIO with I=1
    k = jnp.tile(k, (1, 1, 1, c))
    return jax.lax.conv_general_dilated(
        batch,
        k,
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


def gaussian_blur(batch: jnp.ndarray, ksize: int, sigma: float) -> jnp.ndarray:
    """cv2.GaussianBlur analog — runs on the MXU as a depthwise conv."""
    return _depthwise_conv2d(batch, jnp.asarray(gaussian_kernel(ksize, sigma)))


def box_blur(batch: jnp.ndarray, kh: int, kw: int) -> jnp.ndarray:
    """cv2.blur analog — ImageTransformer Blur (:96-110)."""
    k = jnp.full((kh, kw), 1.0 / (kh * kw), dtype=batch.dtype)
    return _depthwise_conv2d(batch, k)


def threshold(batch: jnp.ndarray, thresh: float, max_val: float, kind: str = "binary") -> jnp.ndarray:
    """cv2.threshold analog — ImageTransformer Threshold (:112-125)."""
    kind = kind.lower()
    if kind == "binary":
        return jnp.where(batch > thresh, max_val, 0.0)
    if kind == "binary_inv":
        return jnp.where(batch > thresh, 0.0, max_val)
    if kind == "trunc":
        return jnp.minimum(batch, thresh)
    if kind == "tozero":
        return jnp.where(batch > thresh, batch, 0.0)
    if kind == "tozero_inv":
        return jnp.where(batch > thresh, 0.0, batch)
    raise ValueError(f"unknown threshold kind {kind!r}")


def normalize(batch: jnp.ndarray, mean: Sequence[float], std: Sequence[float],
              scale: float = 1.0) -> jnp.ndarray:
    """(x*scale - mean)/std channelwise — the fused tail of every DL feed."""
    mean = jnp.asarray(mean, dtype=batch.dtype)
    std = jnp.asarray(std, dtype=batch.dtype)
    return (batch * scale - mean) / std


def hwc_to_chw_flat(batch: jnp.ndarray) -> jnp.ndarray:
    """[B,H,W,C] -> [B, C*H*W] flat vector, CHW order — UnrollImage semantics
    (core/image/UnrollImage.scala:30-55: output index c*h*w layout)."""
    b = batch.shape[0]
    return jnp.transpose(batch, (0, 3, 1, 2)).reshape(b, -1)


def chw_flat_to_hwc(flat: jnp.ndarray, height: int, width: int, channels: int) -> jnp.ndarray:
    """Inverse of hwc_to_chw_flat — UnrollImage.roll (UnrollImage.scala)."""
    b = flat.shape[0]
    return jnp.transpose(
        flat.reshape(b, channels, height, width), (0, 2, 3, 1)
    )
