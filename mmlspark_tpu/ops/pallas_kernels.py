"""Pallas TPU kernels for the image-preprocessing hot path.

Reference: the OpenCV Mat pipeline (opencv/.../ImageTransformer.scala:222-276)
+ UnrollImage (core/image/UnrollImage.scala:30-55) run per-row on JVM
threads; BASELINE.json's north star is this preprocessing feeding the
ImageFeaturizer.  Here the normalize + HWC->CHW unroll (the last host-side
step before the backbone) is ONE fused VMEM-resident Pallas kernel — a
single HBM read and write per image instead of XLA's worst case of separate
normalize/transpose materializations.

On CPU (tests/CI) the kernels run with `interpret=True`; on TPU they compile
to Mosaic.  `fused_normalize_unroll` is numerically identical to the XLA
composition (ops.image.normalize + hwc_to_chw_flat).
"""
from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_normalize_unroll", "fused_resize_normalize",
           "pallas_available"]


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@partial(jax.jit, static_argnames=("mean", "std"))
def _fused_normalize_unroll_pallas(batch, mean: tuple, std: tuple):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, c = batch.shape
    mean_a = jnp.asarray(mean, batch.dtype).reshape(1, 1, c)
    inv_std = jnp.asarray(
        [1.0 / s for s in std], batch.dtype
    ).reshape(1, 1, c)

    def kernel(x_ref, mean_ref, inv_ref, out_ref):
        x = (x_ref[0] - mean_ref[:]) * inv_ref[:]  # (h, w, c) in VMEM
        out_ref[0] = jnp.transpose(x, (2, 0, 1))  # CHW

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, c, h, w), batch.dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, c, h, w), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(batch, mean_a, inv_std)
    return out.reshape(b, c * h * w)


@partial(jax.jit, static_argnames=("h_out", "w_out", "mean", "std"))
def _fused_resize_normalize_pallas(batch, h_out: int, w_out: int,
                                   mean: tuple, std: tuple):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h_in, w_in, c = batch.shape
    # separable bilinear resize as two dense matmuls: out = Ry @ X @ Rx^T.
    # The weight matrices are the true jax.image.resize row weights
    # (resizing an identity matrix along one axis), so the kernel is
    # numerically the library resize — but cast + resize + normalize is one
    # VMEM-resident pass (no full-size f32 intermediate in HBM), and the
    # interpolation runs on the MXU.
    ry = _resize_weights(h_in, h_out)               # [h_out, h_in]
    rx = _resize_weights(w_in, w_out)               # [w_out, w_in]
    mean_a = jnp.asarray(mean, jnp.float32).reshape(1, 1, c)
    inv_std = jnp.asarray([1.0 / s for s in std], jnp.float32).reshape(1, 1, c)

    def kernel(x_ref, ry_ref, rx_ref, mean_ref, inv_ref, out_ref):
        x = x_ref[0].astype(jnp.float32)            # [H, W, C]
        t = jnp.dot(ry_ref[:], x.reshape(h_in, w_in * c),
                    preferred_element_type=jnp.float32)      # [h, W*C]
        t = t.reshape(h_out, w_in, c)
        t = jnp.transpose(t, (1, 0, 2)).reshape(w_in, h_out * c)
        u = jnp.dot(rx_ref[:], t,
                    preferred_element_type=jnp.float32)      # [w, h*C]
        u = jnp.transpose(u.reshape(w_out, h_out, c), (1, 0, 2))
        out_ref[0] = (u - mean_ref[:]) * inv_ref[:]

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h_out, w_out, c), jnp.float32),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h_in, w_in, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_out, h_in), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((w_out, w_in), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, h_out, w_out, c), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(batch, ry, rx, mean_a, inv_std)


def _resize_weights(n_in: int, n_out: int) -> jnp.ndarray:
    """[n_out, n_in] linear-interpolation weights with jax.image.resize's
    exact convention (resize the identity along one axis)."""
    if n_in == n_out:
        return jnp.eye(n_in, dtype=jnp.float32)
    eye = jnp.eye(n_in, dtype=jnp.float32)
    return jax.image.resize(eye, (n_out, n_in), method="linear")


# one image must stage in VMEM (~16MB/core): input block + its f32 cast
# + the resized output; larger inputs take the XLA composition instead of
# failing the Mosaic compile with a resource error
PALLAS_IMAGE_VMEM_BUDGET = 8 * 1024 * 1024


def _fits_vmem(in_shape, h_out: int, w_out: int, itemsize: int) -> bool:
    _, h, w, c = in_shape
    staged = h * w * c * (itemsize + 4) + h_out * w_out * c * 4
    return staged <= PALLAS_IMAGE_VMEM_BUDGET


def fused_resize_normalize(batch: jnp.ndarray, h_out: int, w_out: int,
                           mean: Sequence[float] = (0.0,),
                           std: Sequence[float] = (1.0,)) -> jnp.ndarray:
    """uint8/f32 [B,H,W,C] -> f32 [B,h,w,C]: cast + bilinear resize +
    per-channel normalize in one fused VMEM pass (the ImageTransformer
    resize/normalize tail of SURVEY P2; ImageTransformer.scala:127-146 +
    the normalize feed).  Falls back to the XLA composition when Pallas is
    unavailable, when the per-image block would overflow VMEM, or when no
    resize is needed (identity-size inputs are a pure cast+normalize — two
    identity matmuls would be wasted MXU work)."""
    batch = jnp.asarray(batch)
    _, h_in, w_in, c = batch.shape
    mean = tuple(float(m) for m in np.broadcast_to(np.asarray(mean), (c,)))
    std = tuple(float(s) for s in np.broadcast_to(np.asarray(std), (c,)))
    same_size = h_in == h_out and w_in == w_out
    if (not pallas_available() or same_size
            or not _fits_vmem(batch.shape, h_out, w_out, batch.dtype.itemsize)):
        from .image import normalize, resize

        x = batch.astype(jnp.float32)
        if not same_size:
            x = resize(x, h_out, w_out)
        return normalize(x, mean, std)
    return _fused_resize_normalize_pallas(batch, h_out, w_out, mean, std)


def fused_normalize_unroll(batch: jnp.ndarray,
                           mean: Sequence[float] = (0.0,),
                           std: Sequence[float] = (1.0,)) -> jnp.ndarray:
    """(B, H, W, C) -> (B, C*H*W) with per-channel (x - mean) / std fused in.

    Falls back to the XLA composition when Pallas is unavailable.
    """
    batch = jnp.asarray(batch)
    c = batch.shape[-1]
    mean = tuple(float(m) for m in np.broadcast_to(np.asarray(mean), (c,)))
    std = tuple(float(s) for s in np.broadcast_to(np.asarray(std), (c,)))
    if not pallas_available():  # pragma: no cover
        from .image import hwc_to_chw_flat, normalize

        return hwc_to_chw_flat(normalize(batch, mean, std))
    return _fused_normalize_unroll_pallas(batch, mean, std)
