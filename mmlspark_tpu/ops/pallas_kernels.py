"""Pallas TPU kernels for the image-preprocessing hot path.

Reference: the OpenCV Mat pipeline (opencv/.../ImageTransformer.scala:222-276)
+ UnrollImage (core/image/UnrollImage.scala:30-55) run per-row on JVM
threads; BASELINE.json's north star is this preprocessing feeding the
ImageFeaturizer.  Here the normalize + HWC->CHW unroll (the last host-side
step before the backbone) is ONE fused VMEM-resident Pallas kernel — a
single HBM read and write per image instead of XLA's worst case of separate
normalize/transpose materializations.

On CPU (tests/CI) the kernels run with `interpret=True`; on TPU they compile
to Mosaic.  `fused_normalize_unroll` is numerically identical to the XLA
composition (ops.image.normalize + hwc_to_chw_flat).
"""
from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["fused_normalize_unroll", "fused_resize_normalize",
           "pallas_available"]


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except ImportError:  # pragma: no cover
        return False


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@partial(jax.jit, static_argnames=("mean", "std"))
def _fused_normalize_unroll_pallas(batch, mean: tuple, std: tuple):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, w, c = batch.shape
    mean_a = jnp.asarray(mean, batch.dtype).reshape(1, 1, c)
    inv_std = jnp.asarray(
        [1.0 / s for s in std], batch.dtype
    ).reshape(1, 1, c)

    def kernel(x_ref, mean_ref, inv_ref, out_ref):
        x = (x_ref[0] - mean_ref[:]) * inv_ref[:]  # (h, w, c) in VMEM
        out_ref[0] = jnp.transpose(x, (2, 0, 1))  # CHW

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, c, h, w), batch.dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, c, h, w), lambda i: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(batch, mean_a, inv_std)
    return out.reshape(b, c * h * w)


def _pad_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _resize_weights_np(n_in: int, n_out: int) -> np.ndarray:
    """[n_out, n_in] numpy linear-interpolation weights, bit-matching
    jax.image.resize(method="linear") (half-pixel centers, triangle kernel,
    antialiased on downscale) — pure numpy so it is safe to call at trace
    time inside an enclosing jit."""
    if n_in == n_out:
        return np.eye(n_in, dtype=np.float32)
    scale = n_out / n_in
    kernel_scale = max(1.0 / scale, 1.0)  # antialias widens on downscale
    sample_f = (np.arange(n_out, dtype=np.float64) + 0.5) / scale - 0.5
    x = np.abs(sample_f[None, :] - np.arange(n_in, dtype=np.float64)[:, None])
    w = np.maximum(0.0, 1.0 - x / kernel_scale)     # triangle kernel
    total = w.sum(axis=0, keepdims=True)
    w = np.where(total > 0, w / np.where(total == 0, 1.0, total), 0.0)
    return np.ascontiguousarray(w.T, dtype=np.float32)


@lru_cache(maxsize=8)  # entries hold multi-MB weight matrices; keep small
def _resize_consts(h_in: int, w_in: int, c: int, h_out: int, w_out: int,
                   mean: tuple, std: tuple):
    """Host-built (numpy) padded weight matrices for the 2D kernel — the
    resize+normalize special case of _affine_consts (identity channel mix)."""
    return _affine_consts(
        _resize_weights_np(h_in, h_out),
        _resize_weights_np(w_in, w_out),
        np.eye(c, dtype=np.float32),
        np.asarray(mean, np.float32),
        (1.0 / np.asarray(std, np.float32)).astype(np.float32))


def _fused_resize_normalize_pallas(batch, h_out: int, w_out: int,
                                   mean: tuple, std: tuple):
    _, _, _, c = batch.shape
    consts = _resize_consts(batch.shape[1], batch.shape[2], c,
                            h_out, w_out, mean, std)
    return _fused_resize_normalize_run(
        batch, *map(jnp.asarray, consts), h_out=h_out, w_out=w_out)


@partial(jax.jit, static_argnames=("h_out", "w_out", "c_out"))
def _fused_resize_normalize_run(batch, ry_p, m, mean_t, inv_t,
                                *, h_out: int, w_out: int,
                                c_out: Optional[int] = None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h_in, w_in, c = batch.shape
    # Mosaic-legal formulation: the HWC image is its natural 2D memory view
    # [H, W*C] (channels interleaved along the lane dimension), so the whole
    # kernel is plain 2D matmuls — no in-kernel reshape/transpose, which
    # Mosaic's vector layouts reject for C=3-minor arrays.  Separable
    # bilinear resize becomes out = Ry @ X @ M, where Ry is the true
    # jax.image.resize height weights and M is the width weights interleaved
    # per channel: M[w*c+ch, w'*c+ch'] = Rx[w', w] * (ch == ch').  Both
    # operands are padded up to the (8, 128) tile grid; padded rows/cols
    # carry zero weights so the result is exact, and the pads are sliced off
    # outside the kernel (cheap XLA slice of the small output).
    #
    # One HBM read of the uint8 input + one HBM write of the f32 output per
    # image: cast + resize + normalize never materialize full-size f32
    # intermediates, and the interpolation runs on the MXU.
    kin = w_in * c
    kout = w_out * (c_out if c_out is not None else c)
    h_out_p, kout_p = ry_p.shape[0], m.shape[1]
    h_in_p, kin_p = ry_p.shape[1], m.shape[0]

    x2 = batch.reshape(b, h_in, kin)
    if (h_in_p, kin_p) != (h_in, kin):
        x2 = jnp.pad(x2, ((0, 0), (0, h_in_p - h_in), (0, kin_p - kin)))

    def kernel(x_ref, ry_ref, m_ref, mean_ref, inv_ref, out_ref):
        x = x_ref[0]                                # [H_p, (W*C)_p]
        if x.dtype == jnp.uint8:
            # Mosaic can't lower uint8->float32 directly; widen via int32
            # (uint8 values fit losslessly)
            x = x.astype(jnp.int32)
        x = x.astype(jnp.float32)
        # HIGHEST: full-f32 accumulation on the MXU (3-pass bf16) — keeps
        # the interpolation within one uint8 LSB of the XLA reference
        t = jnp.dot(ry_ref[:], x, preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        u = jnp.dot(t, m_ref[:], preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        out_ref[0] = (u - mean_ref[:]) * inv_ref[:]

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h_out_p, kout_p), jnp.float32),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h_in_p, kin_p), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h_out_p, h_in_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kin_p, kout_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kout_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kout_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, h_out_p, kout_p), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(x2, ry_p, m, mean_t, inv_t)
    return out[:, :h_out, :kout].reshape(
        b, h_out, w_out, c_out if c_out is not None else c)


# one image must stage in VMEM (~16MB/core): input block + its f32 cast
# + the resized output; larger inputs take the XLA composition instead of
# failing the Mosaic compile with a resource error
PALLAS_IMAGE_VMEM_BUDGET = 8 * 1024 * 1024


def _staged_bytes(h_in: int, w_in: int, c_in: int, h_out: int, w_out: int,
                  c_out: int, itemsize: int) -> int:
    """Per-grid-step VMEM estimate for the 2D affine kernel."""
    kin, kout = _pad_up(w_in * c_in, 128), _pad_up(w_out * c_out, 128)
    h_p, ho_p = _pad_up(h_in, 8), _pad_up(h_out, 8)
    # uint8 inputs widen through an int32 intermediate before the f32 cast
    # (Mosaic has no direct u8->f32), staging an extra 4 bytes/elem
    widen = h_p * kin * 4 if itemsize == 1 else 0
    return (widen
            + h_p * kin * (itemsize + 4)      # input block + f32 cast
            + ho_p * h_p * 4                  # height weights ry_p
            + ho_p * kin * 4                  # height-resized intermediate
            + kin * kout * 4                  # interleaved width weights
            + 2 * kout * 4                    # mean / inv-std row vectors
            + ho_p * kout * 4)                # output block


def _fits_vmem(in_shape, h_out: int, w_out: int, itemsize: int) -> bool:
    _, h, w, c = in_shape
    return _staged_bytes(h, w, c, h_out, w_out, c,
                         itemsize) <= PALLAS_IMAGE_VMEM_BUDGET


def fused_resize_normalize(batch: jnp.ndarray, h_out: int, w_out: int,
                           mean: Sequence[float] = (0.0,),
                           std: Sequence[float] = (1.0,)) -> jnp.ndarray:
    """uint8/f32 [B,H,W,C] -> f32 [B,h,w,C]: cast + bilinear resize +
    per-channel normalize in one fused VMEM pass (the ImageTransformer
    resize/normalize tail of SURVEY P2; ImageTransformer.scala:127-146 +
    the normalize feed).  Falls back to the XLA composition when Pallas is
    unavailable, when the per-image block would overflow VMEM, or when no
    resize is needed (identity-size inputs are a pure cast+normalize — two
    identity matmuls would be wasted MXU work)."""
    batch = jnp.asarray(batch)
    _, h_in, w_in, c = batch.shape
    mean = tuple(float(m) for m in np.broadcast_to(np.asarray(mean), (c,)))
    std = tuple(float(s) for s in np.broadcast_to(np.asarray(std), (c,)))
    same_size = h_in == h_out and w_in == w_out
    if (not pallas_available() or same_size
            or not _fits_vmem(batch.shape, h_out, w_out, batch.dtype.itemsize)):
        from .image import normalize, resize

        x = batch.astype(jnp.float32)
        if not same_size:
            x = resize(x, h_out, w_out)
        return normalize(x, mean, std)
    return _fused_resize_normalize_pallas(batch, h_out, w_out, mean, std)


def fused_normalize_unroll(batch: jnp.ndarray,
                           mean: Sequence[float] = (0.0,),
                           std: Sequence[float] = (1.0,)) -> jnp.ndarray:
    """(B, H, W, C) -> (B, C*H*W) with per-channel (x - mean) / std fused in.

    Uses the Pallas kernel in interpret mode off-TPU (the reference
    semantics); on real TPU hardware it takes the XLA composition — the
    C=3-minor (1,c,h,w) output block can never satisfy Mosaic's (8,128)
    tile rules, and XLA already fuses normalize+transpose into one HBM
    pass for this pattern.
    """
    batch = jnp.asarray(batch)
    c = batch.shape[-1]
    mean = tuple(float(m) for m in np.broadcast_to(np.asarray(mean), (c,)))
    std = tuple(float(s) for s in np.broadcast_to(np.asarray(std), (c,)))
    if not pallas_available() or jax.default_backend() == "tpu":
        from .image import hwc_to_chw_flat, normalize

        return hwc_to_chw_flat(normalize(batch, mean, std))
    return _fused_normalize_unroll_pallas(batch, mean, std)


# ---------------------------------------------------------------------------
# Fused affine image pipelines: every separable-linear ImageTransformer op
# (crop / resize / flip / separable blur / color conversion) is a per-axis
# matrix, so an entire op chain composes into the SAME two-matmul kernel —
# out = (A_h @ X @ (A_w ⊗ C)) affine-tail — one HBM read and one write for
# the whole pipeline (ImageTransformer.scala:282-400 runs these per-row on
# OpenCV Mats; XLA runs them as separate fused loops; this is one pass).
# ---------------------------------------------------------------------------

def _color_mats():
    """Channel-mixing matrices matching ops.image.color_convert exactly
    (gray weights come from the same _BGR2GRAY constant, so the fused and
    XLA paths can never diverge)."""
    from .image import _BGR2GRAY

    gray_bgr = np.asarray(_BGR2GRAY, np.float64).reshape(3, 1)
    return {
        "bgr2rgb": np.eye(3)[:, ::-1],
        "rgb2bgr": np.eye(3)[:, ::-1],
        "bgr2gray": gray_bgr,
        "rgb2gray": gray_bgr[::-1],
        "gray2bgr": np.ones((1, 3)),
        "gray2rgb": np.ones((1, 3)),
    }


def _conv_same_matrix(n: int, k1d: np.ndarray) -> np.ndarray:
    """[n, n] zero-padded SAME-convolution (Toeplitz) matrix matching
    lax.conv SAME semantics: pad_low = (k-1)//2."""
    k = len(k1d)
    pad_low = (k - 1) // 2
    t = np.zeros((n, n), np.float64)
    for i in range(n):
        for tap in range(k):
            j = i + tap - pad_low
            if 0 <= j < n:
                t[i, j] += k1d[tap]
    return t


def build_affine_pipeline(stages, h_in: int, w_in: int, c_in: int):
    """Compose an ImageTransformer op list into (A_h, A_w, C, mean, inv)
    where out = (A_h @ X @ A_w^T per-axis, channels mixed by C) * inv - mean*inv.
    Returns None when any op is not expressible (threshold, mid-chain
    normalize) — the caller falls back to the XLA composition."""
    from .image import gaussian_kernel

    a_h = np.eye(h_in, dtype=np.float64)
    a_w = np.eye(w_in, dtype=np.float64)
    cmat = np.eye(c_in, dtype=np.float64)
    h, w, c = h_in, w_in, c_in
    mean = None
    std = None
    scale = 1.0
    mixing = False  # a real interpolation/filter — pure permutation or
    # selection chains (flip/crop/color swap) are faster as XLA views than
    # as dense matmuls, so those decline fusion
    for name, kw in stages or []:
        if mean is not None:
            return None  # ops after normalize: keep the XLA path
        if name == "resize":
            if kw.get("method", "linear") != "linear":
                return None
            nh, nw = int(kw["height"]), int(kw["width"])
            mixing = mixing or nh != h or nw != w
            a_h = _resize_weights_np(h, nh).astype(np.float64) @ a_h
            a_w = _resize_weights_np(w, nw).astype(np.float64) @ a_w
            h, w = nh, nw
        elif name == "crop":
            x0, y0 = int(kw["x"]), int(kw["y"])
            cw, ch_ = int(kw["width"]), int(kw["height"])
            a_h = a_h[y0:y0 + ch_]
            a_w = a_w[x0:x0 + cw]
            h, w = a_h.shape[0], a_w.shape[0]
        elif name == "centerCrop":
            ch_, cw = int(kw["height"]), int(kw["width"])
            y0 = max((h - ch_) // 2, 0)
            x0 = max((w - cw) // 2, 0)
            a_h = a_h[y0:y0 + ch_]
            a_w = a_w[x0:x0 + cw]
            h, w = a_h.shape[0], a_w.shape[0]
        elif name == "flip":
            if kw.get("flipLeftRight", True):
                a_w = a_w[::-1]
            if kw.get("flipUpDown", False):
                a_h = a_h[::-1]
        elif name == "blur":
            kh, kw_ = int(kw["height"]), int(kw["width"])
            a_h = _conv_same_matrix(h, np.full(kh, 1.0 / kh)) @ a_h
            a_w = _conv_same_matrix(w, np.full(kw_, 1.0 / kw_)) @ a_w
            mixing = True
        elif name == "gaussianKernel":
            k2d = gaussian_kernel(int(kw["apertureSize"]), float(kw["sigma"]))
            # gaussian_kernel is outer(g, g): recover the separable 1-D taps
            g = np.sqrt(np.diag(k2d.astype(np.float64)))
            a_h = _conv_same_matrix(h, g) @ a_h
            a_w = _conv_same_matrix(w, g) @ a_w
            mixing = True
        elif name == "colorFormat":
            m = _color_mats().get(kw["format"].lower())
            if m is None or m.shape[0] != c:
                return None
            cmat = cmat @ m
            c = m.shape[1]
        elif name == "normalize":
            scale = float(kw.get("scale", 1.0))
            if scale == 0.0:
                # degenerate: (u*0 - mean)/std is constant, which the
                # (u - mean/scale)*(scale/std) folding can't express
                return None
            mean = np.broadcast_to(np.asarray(kw["mean"], np.float64), (c,))
            std = np.broadcast_to(np.asarray(kw["std"], np.float64), (c,))
        else:
            return None  # threshold and anything unknown
    if not mixing:
        return None  # view-only chains: XLA composition wins
    if mean is None:
        mean = np.zeros(c)
        std = np.ones(c)
    # (u*scale - mean)/std == (u - mean/scale) * (scale/std); scale == 0
    # declined fusion above
    mean_eff = mean / scale
    inv_eff = scale / std
    return (a_h.astype(np.float32), a_w.astype(np.float32),
            cmat.astype(np.float32), mean_eff.astype(np.float32),
            inv_eff.astype(np.float32))


def _affine_consts(a_h, a_w, cmat, mean_eff, inv_eff):
    """Pad composed matrices to the (8, 128) tile grid and interleave the
    width/channel matrices for the 2D kernel."""
    h_out, h_in = a_h.shape
    w_out, w_in = a_w.shape
    c_in, c_out = cmat.shape
    kin, kout = w_in * c_in, w_out * c_out
    h_in_p, kin_p = _pad_up(h_in, 8), _pad_up(kin, 128)
    h_out_p, kout_p = _pad_up(h_out, 8), _pad_up(kout, 128)
    ry_p = np.zeros((h_out_p, h_in_p), np.float32)
    ry_p[:h_out, :h_in] = a_h
    m = np.zeros((kin_p, kout_p), np.float32)
    for ci in range(c_in):
        for co in range(c_out):
            if cmat[ci, co] != 0.0:
                m[ci:kin:c_in, co:kout:c_out] = a_w.T * cmat[ci, co]
    mean_t = np.zeros((1, kout_p), np.float32)
    inv_t = np.zeros((1, kout_p), np.float32)
    for co in range(c_out):
        mean_t[0, co:kout:c_out] = mean_eff[co]
        inv_t[0, co:kout:c_out] = inv_eff[co]
    return ry_p, m, mean_t, inv_t


def affine_pipeline_fits_vmem(consts, itemsize: int = 4) -> bool:
    a_h, a_w, cmat, _, _ = consts
    return _staged_bytes(a_h.shape[1], a_w.shape[1], cmat.shape[0],
                         a_h.shape[0], a_w.shape[0], cmat.shape[1],
                         itemsize) <= PALLAS_IMAGE_VMEM_BUDGET


def freeze_stages(stages) -> tuple:
    """Hashable form of an ImageTransformer op list (lists -> tuples)."""

    def fz(v):
        if isinstance(v, np.ndarray):
            return tuple(v.tolist())
        if isinstance(v, (list, tuple)):
            return tuple(fz(x) for x in v)
        return v

    return tuple((name, tuple(sorted((k, fz(v)) for k, v in kw.items())))
                 for name, kw in (stages or []))


@lru_cache(maxsize=16)
def affine_plan(frozen_stages: tuple, h_in: int, w_in: int, c_in: int,
                itemsize: int = 4):
    """Composed + padded + device-resident kernel constants for a frozen op
    list and input shape — or None when the chain isn't fusable (nonlinear
    op, view-only chain, VMEM overflow).  `itemsize` is the BATCH dtype's
    (uint8 stages an extra int32 widen in VMEM — see _staged_bytes).
    Cached so repeated batches reuse one host composition and one device
    upload."""
    consts = build_affine_pipeline(
        [(name, dict(kw)) for name, kw in frozen_stages], h_in, w_in, c_in)
    if consts is None or not affine_pipeline_fits_vmem(consts, itemsize):
        return None
    a_h, a_w, cmat, mean_eff, inv_eff = consts
    padded = tuple(jnp.asarray(p)
                   for p in _affine_consts(a_h, a_w, cmat, mean_eff, inv_eff))
    return padded, (a_h.shape[0], a_w.shape[0], cmat.shape[1])


def fused_affine_apply(batch: jnp.ndarray, plan) -> jnp.ndarray:
    """Run a cached affine plan (from affine_plan) as one VMEM-resident
    kernel pass over [B,H,W,C]."""
    padded, (h_out, w_out, c_out) = plan
    return _fused_resize_normalize_run(
        batch, *padded, h_out=h_out, w_out=w_out, c_out=c_out)
