"""Pallas TPU kernel for paged-KV decode attention.

The XLA paged decode path (models/transformer.py `_Block.__call__`,
page_table branch) gathers every slot's pages into a logical
[B, L, Hkv, D] view per step — correct, but the cache READ touches all
MP pages per slot whether live or not.  This kernel walks the page table
instead (the vLLM paged-attention shape, TPU-style):

  - grid = (B, MP), page index j innermost.  The K/V block specs select
    the PHYSICAL page via the scalar-prefetched page table
    (`PrefetchScalarGridSpec`): block j of slot b is pool page
    table[b, j].  Pages past the slot's live length all map to the
    write-trash page 0, and Mosaic skips the HBM->VMEM copy when
    consecutive iterations map to the same block — so DMA volume scales
    with LIVE pages, not MP.
  - one grid step processes ALL heads of one page: scores/output are
    elementwise multiply + reduce (VPU work, no batched dot_general —
    decode attention is bandwidth-bound, the MXU is irrelevant here),
    masked by the slot position, accumulated across pages with the
    online-softmax recurrence in VMEM scratch (same shape as
    attention_kernels.py).

Exactness: parity vs the XLA gather path is enforced in
tests/test_paged_attention.py (interpret mode on CPU; the on-chip Mosaic
compile+parity rides `mfu_sweep --decode`'s paged case).  Callers route
through `paged_decode_attention`, which owns the dispatch: the
conservative shape/VMEM gate (`paged_kernel_ok`) keeps ineligible
configs — GQA pools, odd head dims, oversized pages — on the XLA
composition.  If a gated-in shape still trips Mosaic on real hardware
(the gate is an estimate), the failure surfaces at the serving step's
first compile; `MMLSPARK_NO_PAGED_KERNEL=1` forces the gather path
without a code change.  Scope of that switch: the env var is read at
TRACE time, so it must be set BEFORE the serving process compiles its
first paged step — flipping it in an already-running server does
nothing for programs XLA has already compiled (restart the process, or
clear the jit caches with `jax.clear_caches()` and let the next step
retrace).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .pallas_kernels import (
    PALLAS_IMAGE_VMEM_BUDGET,
    _interpret,
    pallas_available,
)

__all__ = ["paged_decode_attention", "paged_decode_attention_int8",
           "paged_kernel_ok"]

_NEG_INF = -1e30
_LANE = 128


def paged_kernel_ok(q, k_pool) -> bool:
    """Will the Pallas page-walk kernel take this shape?  q [B, H, D],
    k_pool [NP, page, H, D].  Conservative: lane-friendly head dim,
    sublane-friendly page size, MHA pools only (GQA expands head count
    between q and pool — the XLA gather path serves it), and the
    per-step working set must fit the VMEM budget (an oversized page
    config must route to the gather, not die in Mosaic)."""
    import os

    if not pallas_available() or os.environ.get("MMLSPARK_NO_PAGED_KERNEL"):
        return False
    b, h, d = q.shape
    np_, page, hk, dk = k_pool.shape
    if (hk, dk) != (h, d):
        return False
    if not (d % 64 == 0 and page % 8 == 0 and page >= 8):
        return False
    item = k_pool.dtype.itemsize
    staged = (2 * page * h * d * item     # K + V page blocks (DMA)
              # f32 staging is charged regardless of pool dtype: the int8
              # kernel materializes f32 dequant copies of both blocks, so
              # its working set is NOT smaller than f32's — an int8 gate
              # looser than the f32 gate would promise Mosaic shapes it
              # rejects
              + 4 * page * h * d * 4      # dequant copies + mul intermediates
              + 2 * h * d * 4             # q block + o scratch (f32)
              + 4 * page * h * 4          # scores/probs + scale blocks
              + 2 * h * _LANE * 4)        # m / l scratch
    return staged <= PALLAS_IMAGE_VMEM_BUDGET


@partial(jax.jit, static_argnames=())
def _paged_pallas(q, k_pool, v_pool, page_table, pos):
    """q [B, H, D]; pools [NP, page, H, D]; table [B, MP] i32; pos [B]
    i32 -> [B, H, D] f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    np_, page, _, _ = k_pool.shape
    mp = page_table.shape[1]
    scale = 1.0 / float(d) ** 0.5

    def kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
               o_acc, m_acc, l_acc):
        bi = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            o_acc[...] = jnp.zeros_like(o_acc)
            m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
            l_acc[...] = jnp.zeros_like(l_acc)

        p_b = pos_ref[bi]
        # pages whose first position is past the slot's write position
        # hold nothing visible — skip their compute entirely (their DMA
        # was already skipped: the index_map parks them on page 0)
        @pl.when(j * page <= p_b)
        def _update():
            qb = q_ref[0]                       # [H, D]
            kb = k_ref[0]                       # [page, H, D]
            vb = v_ref[0]
            # scores[p, h] = sum_d k[p,h,d] * q[h,d] — VPU reduce, no
            # batched dot (decode reads dominate; MXU is irrelevant)
            sc = jnp.sum(kb.astype(jnp.float32) *
                         qb[None].astype(jnp.float32), axis=-1) * scale
            rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
            sc = jnp.where(j * page + rows <= p_b, sc, _NEG_INF)
            # online softmax over the page axis, stats per head kept
            # lane-broadcast in [H, LANE] scratch (axis-0 stats of the
            # [page, H] tile, swapped into head-major [H, 1])
            m_prev = jnp.max(m_acc[...], axis=-1, keepdims=True)  # [H, 1]
            l_prev = jnp.max(l_acc[...], axis=-1, keepdims=True)
            m_cur = jnp.swapaxes(jnp.max(sc, axis=0, keepdims=True), 0, 1)
            m_new = jnp.maximum(m_prev, m_cur)                    # [H, 1]
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(sc - jnp.swapaxes(m_new, 0, 1))           # [page, H]
            l_new = l_prev * corr + jnp.swapaxes(
                jnp.sum(p, axis=0, keepdims=True), 0, 1)
            o_acc[...] = (o_acc[...] * corr +
                          jnp.sum(p[:, :, None] * vb.astype(jnp.float32),
                                  axis=0))
            m_acc[...] = jnp.broadcast_to(m_new, m_acc.shape)
            l_acc[...] = jnp.broadcast_to(l_new, l_acc.shape)

        @pl.when(j == mp - 1)
        def _finish():
            l_fin = jnp.max(l_acc[...], axis=-1, keepdims=True)
            o_ref[0] = o_acc[...] / jnp.maximum(l_fin, 1e-20)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,        # page_table (flat) + pos
        grid=(b, mp),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bi, j, tbl, pos: (bi, 0, 0)),
            pl.BlockSpec((1, page, h, d),
                         lambda bi, j, tbl, pos: (tbl[bi * mp + j], 0, 0, 0)),
            pl.BlockSpec((1, page, h, d),
                         lambda bi, j, tbl, pos: (tbl[bi * mp + j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda bi, j, tbl, pos: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, _LANE), jnp.float32),
            pltpu.VMEM((h, _LANE), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(page_table.reshape(-1), pos, q, k_pool, v_pool)


@partial(jax.jit, static_argnames=())
def _paged_pallas_int8(q, kq_pool, ks_pool, vq_pool, vs_pool,
                       page_table, pos):
    """int8 variant: pools are int8 [NP, page, H, D] with per-(pos, head)
    f32 scales [NP, page, H] (ops/quant.quantize_kv_row rows).  The
    dequant multiplies ride the tiny [page, H] score/prob tensors —
    exactly `_cache_attention`'s quant factoring — so the HBM read stays
    1/4 of f32."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    np_, page, _, _ = kq_pool.shape
    mp = page_table.shape[1]
    scale = 1.0 / float(d) ** 0.5

    def kernel(tbl_ref, pos_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref,
               o_ref, o_acc, m_acc, l_acc):
        bi = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            o_acc[...] = jnp.zeros_like(o_acc)
            m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
            l_acc[...] = jnp.zeros_like(l_acc)

        p_b = pos_ref[bi]

        @pl.when(j * page <= p_b)
        def _update():
            qb = q_ref[0].astype(jnp.float32)    # [H, D]
            kb = kq_ref[0].astype(jnp.float32)   # [page, H, D] int8->f32
            vb = vq_ref[0].astype(jnp.float32)
            ksb = ks_ref[0]                      # [page, H] f32 scales
            vsb = vs_ref[0]
            sc = jnp.sum(kb * qb[None], axis=-1) * ksb * scale
            rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
            sc = jnp.where(j * page + rows <= p_b, sc, _NEG_INF)
            m_prev = jnp.max(m_acc[...], axis=-1, keepdims=True)
            l_prev = jnp.max(l_acc[...], axis=-1, keepdims=True)
            m_cur = jnp.swapaxes(jnp.max(sc, axis=0, keepdims=True), 0, 1)
            m_new = jnp.maximum(m_prev, m_cur)
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(sc - jnp.swapaxes(m_new, 0, 1))          # [page, H]
            l_new = l_prev * corr + jnp.swapaxes(
                jnp.sum(p, axis=0, keepdims=True), 0, 1)
            o_acc[...] = (o_acc[...] * corr +
                          jnp.sum((p * vsb)[:, :, None] * vb, axis=0))
            m_acc[...] = jnp.broadcast_to(m_new, m_acc.shape)
            l_acc[...] = jnp.broadcast_to(l_new, l_acc.shape)

        @pl.when(j == mp - 1)
        def _finish():
            l_fin = jnp.max(l_acc[...], axis=-1, keepdims=True)
            o_ref[0] = o_acc[...] / jnp.maximum(l_fin, 1e-20)

    page_spec = pl.BlockSpec(
        (1, page, h, d), lambda bi, j, tbl, pos: (tbl[bi * mp + j], 0, 0, 0))
    scale_spec = pl.BlockSpec(
        (1, page, h), lambda bi, j, tbl, pos: (tbl[bi * mp + j], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mp),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bi, j, tbl, pos: (bi, 0, 0)),
            page_spec, scale_spec, page_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda bi, j, tbl, pos: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, _LANE), jnp.float32),
            pltpu.VMEM((h, _LANE), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, d), jnp.float32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(page_table.reshape(-1), pos, q, kq_pool, ks_pool, vq_pool, vs_pool)


def _xla_paged_int8(q, kq_pool, ks_pool, vq_pool, vs_pool, page_table, pos):
    """Gather fallback with the same quant factoring as _cache_attention."""
    b, h, d = q.shape
    np_, page, hk, _ = kq_pool.shape
    mp = page_table.shape[1]
    L = mp * page
    kq = kq_pool[page_table].reshape(b, L, hk, d)
    vq = vq_pool[page_table].reshape(b, L, hk, d)
    ks = ks_pool[page_table].reshape(b, L, hk)
    vs = vs_pool[page_table].reshape(b, L, hk)
    if hk != h:
        kq = jnp.repeat(kq, h // hk, axis=2)
        vq = jnp.repeat(vq, h // hk, axis=2)
        ks = jnp.repeat(ks, h // hk, axis=2)
        vs = jnp.repeat(vs, h // hk, axis=2)
    sc = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                    kq.astype(jnp.float32))
    sc = sc * ks.transpose(0, 2, 1) / jnp.sqrt(jnp.float32(d))
    valid = jnp.arange(L)[None, None, :] <= pos[:, None, None]
    sc = jnp.where(valid, sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1) * vs.transpose(0, 2, 1)
    return jnp.einsum("bhk,bkhd->bhd", p, vq.astype(jnp.float32))


def paged_decode_attention_int8(q, kq_pool, ks_pool, vq_pool, vs_pool,
                                page_table, pos):
    """int8 paged decode attention (the 4-tuple cache form): page-walk
    kernel when eligible, quant-factored XLA gather otherwise."""
    if paged_kernel_ok(q, kq_pool):
        return _paged_pallas_int8(q, kq_pool, ks_pool, vq_pool, vs_pool,
                                  page_table.astype(jnp.int32),
                                  pos.astype(jnp.int32))
    return _xla_paged_int8(q, kq_pool, ks_pool, vq_pool, vs_pool,
                           page_table, pos)


def _xla_paged(q, k_pool, v_pool, page_table, pos):
    """Reference semantics: gather pages -> masked softmax attention.
    Mirrors models/transformer._cache_attention for the paged branch.
    GQA pools (hk < h) expand to the query head count after the gather."""
    b, h, d = q.shape
    np_, page, hk, _ = k_pool.shape
    mp = page_table.shape[1]
    k_log = k_pool[page_table].reshape(b, mp * page, hk, d)
    v_log = v_pool[page_table].reshape(b, mp * page, hk, d)
    if hk != h:
        k_log = jnp.repeat(k_log, h // hk, axis=2)
        v_log = jnp.repeat(v_log, h // hk, axis=2)
    sc = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                    k_log.astype(jnp.float32)) / jnp.sqrt(jnp.float32(d))
    valid = jnp.arange(mp * page)[None, None, :] <= pos[:, None, None]
    sc = jnp.where(valid, sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", p, v_log.astype(jnp.float32))


def paged_decode_attention(q, k_pool, v_pool, page_table, pos):
    """Single-token paged decode attention: q [B, H, D] over page pools
    [NP, page, H, D] addressed by table [B, MP] at per-slot positions
    `pos` [B].  Pallas page-walk kernel when the shape allows, XLA
    gather otherwise — identical numerics either way."""
    if paged_kernel_ok(q, k_pool):
        return _paged_pallas(q, k_pool, v_pool,
                             page_table.astype(jnp.int32),
                             pos.astype(jnp.int32))
    return _xla_paged(q, k_pool, v_pool, page_table, pos)
