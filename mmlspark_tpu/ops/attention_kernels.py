"""Pallas TPU kernel for the attention hot path.

The dense attention in parallel/ring_attention.full_attention materializes
the [B, H, S, S] score tensor in HBM — at S=4096, bf16, that is 32MB per
(batch, head) of pure bandwidth.  This kernel keeps each query block's
scores VMEM-resident: one HBM read of Q/K/V and one write of O per block,
the flash-attention traffic shape (Liu et al. ring attention's intra-chip
sibling; reference has no analog — its deepest attention is CNTK-era).

Mosaic-friendly formulation (same playbook as pallas_kernels.py):
  - Q/K/V reshaped OUTSIDE the kernel to [B*H, S, D] (no in-kernel
    reshapes), head_dim padded to a 128 multiple (lane tiling).
  - grid = (B*H, S / block_q); each step loads one [block_q, D] Q block
    plus that (b,h)'s whole [S, D] K/V (fits VMEM for S <= ~4k bf16 —
    enforced by a budget check; larger S falls back to XLA).
  - scores/softmax in f32 on the [block_q, S] block; both matmuls via
    dot_general with f32 accumulation; causal mask from broadcasted_iota
    (2D iota is Mosaic-legal, 1D is not).

Training: fused_attention carries a custom VJP whose BACKWARD is the
plain-XLA composition (recompute) — kernel-fast forward, exact XLA
gradients, no second kernel to validate.  Forward-only callers (serving,
featurization) never touch the backward path.

On CPU the kernel runs interpret=True (tests/CI); on TPU it compiles to
Mosaic.  tests/test_attention_kernels.py holds the parity suite; the
on-hardware compile check rides the same real-TPU gate as the image
kernels.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .pallas_kernels import (
    PALLAS_IMAGE_VMEM_BUDGET,
    _interpret,
    _pad_up,
    pallas_available,
)

__all__ = ["fused_attention", "attention_fits_vmem"]

_BLOCK_Q = 128
_LANE = 128


def attention_fits_vmem(s: int, d: int, itemsize: int = 2,
                        block_q: int = _BLOCK_Q) -> bool:
    """Per-grid-step VMEM estimate: K+V at input dtype, Q block, f32
    scores + probabilities, f32 O block."""
    d_p = _pad_up(d, _LANE)
    staged = (2 * s * d_p * itemsize          # K + V
              + block_q * d_p * itemsize      # Q block
              + 2 * block_q * s * 4           # scores + probs (f32)
              + block_q * d_p * 4)            # O accumulator
    return staged <= PALLAS_IMAGE_VMEM_BUDGET


@partial(jax.jit, static_argnames=("causal", "scale"))
def _attention_pallas(q, k, v, causal: bool, scale: float):
    """q,k,v: [BH, S, D_padded] (D padded to a lane multiple) -> [BH, S,
    D_padded] f32.  `scale` is 1/sqrt(TRUE head dim) — the padded D must
    not leak into the softmax temperature."""
    from jax.experimental import pallas as pl

    bh, s, d = q.shape

    def kernel(q_ref, k_ref, v_ref, o_ref, *, scale):
        qb = q_ref[0]                       # [block_q, D]
        kb = k_ref[0]                       # [S, D]
        vb = v_ref[0]
        sc = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, S]
        if causal:
            qi = pl.program_id(1)
            rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
            mask = (qi * q_ref.shape[1] + rows) >= cols
            sc = jnp.where(mask, sc, -jnp.inf)
        m = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_ref[0] = o / l

    block_q = min(_BLOCK_Q, s)
    return pl.pallas_call(
        partial(kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        grid=(bh, s // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=_interpret(),
    )(q, k, v)


def _xla_attention(q, k, v, causal: bool):
    from ..parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal)


def _kernel_ok(q) -> bool:
    b, s, h, d = q.shape
    if not pallas_available():
        return False
    if s % min(_BLOCK_Q, s) or s % 8 or s < 8:
        return False
    # lane padding below d=64 (4x+ wasted MXU work and padded HBM copies)
    # makes the kernel a net loss vs XLA dense — keep small heads on XLA
    if d < 64:
        return False
    return attention_fits_vmem(s, d, q.dtype.itemsize)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_attention(q, k, v, causal: bool = True):
    """Drop-in for full_attention: (B, S, H, D) -> (B, S, H, D) f32.

    VMEM-resident scores on TPU via Pallas (interpret mode elsewhere);
    falls back to the XLA composition when the shape can't take the
    kernel (S not a block multiple, K/V too large for VMEM).  Scale
    uses the TRUE head dim even when D pads to the 128 lane.
    Differentiable: the backward pass is the exact XLA recompute.
    """
    return _fused_attention_fwd(q, k, v, causal)[0]


def _run_kernel(q, k, v, causal: bool):
    b, s, h, d = q.shape
    d_p = _pad_up(d, _LANE)

    def to_bhsd(x):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
        if d_p != d:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, d_p - d)))
        return x

    o = _attention_pallas(to_bhsd(q), to_bhsd(k), to_bhsd(v), causal,
                          1.0 / float(d) ** 0.5)
    o = o[..., :d].reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return o


def _fused_attention_fwd(q, k, v, causal):
    if _kernel_ok(q):
        out = _run_kernel(q, k, v, causal)
    else:
        out = _xla_attention(q, k, v, causal)
    return out, (q, k, v)


def _fused_attention_bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal),
                     q, k, v)
    return vjp(g)


fused_attention.defvjp(_fused_attention_fwd, _fused_attention_bwd)
