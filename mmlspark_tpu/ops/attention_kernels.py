"""Pallas TPU kernel for the attention hot path.

The dense attention in parallel/ring_attention.full_attention materializes
the [B, H, S, S] score tensor in HBM — at S=4096, bf16, that is 32MB per
(batch, head) of pure bandwidth.  This kernel keeps each query block's
scores VMEM-resident: one HBM read of Q/K/V and one write of O per block,
the flash-attention traffic shape (Liu et al. ring attention's intra-chip
sibling; reference has no analog — its deepest attention is CNTK-era).

Mosaic-friendly formulation (same playbook as pallas_kernels.py):
  - Q/K/V reshaped OUTSIDE the kernel to [B*H, S, D] (no in-kernel
    reshapes); head_dim runs NATIVE at 128-multiples and (probe-gated,
    see _native_d64_ok) at 64-mod-128 dims — padding d=64 up to the
    lane would double the QK^T MACs with zeros and materialize 2x-size
    q/k/v/o copies around every call; other dims pad to the 128 lane.
  - grid = (B*H, S/block_q, S/block_k), K innermost: K/V blocks STREAM
    through VMEM while running max / normalizer / unnormalized output
    live in VMEM scratch across the K steps (online softmax, the true
    flash-attention recurrence) — so VMEM use is O(block_q * block_k),
    independent of S; block_k adapts to the largest block tiling S, so
    any 128-multiple sequence length takes the kernel.
  - scores/softmax in f32; both matmuls via dot_general with f32
    accumulation; causal mask from broadcasted_iota (2D iota is
    Mosaic-legal, 1D is not); the m/l running statistics are stored
    lane-broadcast as [block_q, 128] blocks (a bare [block_q] vector
    is not a legal Mosaic tile).

Training: fused_attention carries a custom VJP whose BACKWARD is the
flash-attention backward as two more Pallas kernels (one accumulates
dK/dV streaming Q blocks, one accumulates dQ streaming K blocks),
recomputing each score block in VMEM from the forward's saved
logsumexp — the dense-XLA backward materialized f32 [B, H, S, S]
score tensors per layer and was measured to be 71% of the whole LM
train step on a v5e (tools/lm_ablate.py).  Shapes the forward kernel
rejects keep the exact XLA-recompute backward.

On CPU the kernel runs interpret=True (tests/CI); on TPU it compiles to
Mosaic.  tests/test_attention_kernels.py holds the parity suite; the
on-hardware compile check rides the same real-TPU gate as the image
kernels.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from .pallas_kernels import (
    PALLAS_IMAGE_VMEM_BUDGET,
    _interpret,
    _pad_up,
    pallas_available,
)

__all__ = ["fused_attention", "attention_fits_vmem", "kernel_ok"]

_BLOCK_Q = 128
_BLOCK_K = 512
_LANE = 128
_NEG_INF = -1e30  # finite stand-in: -inf arithmetic is fragile on Mosaic


def _pick_block_k(s: int) -> int:
    """Largest K block that tiles s — any 128-multiple S gets a kernel."""
    for blk in (_BLOCK_K, 256, 128):
        if s >= blk and s % blk == 0:
            return blk
    return s  # s < 128: single block (s itself must divide by 8)


def attention_fits_vmem(s: int, d: int, itemsize: int = 2,
                        block_q: int = _BLOCK_Q,
                        block_k: int = _BLOCK_K) -> bool:
    """Per-grid-step VMEM estimate — O(block_q * block_k), NOT O(S):
    K/V blocks stream while the accumulators persist.  Taking the kernel
    path commits callers to the flash BACKWARD too (custom_vjp), whose
    dK/dV kernel stages the most: both estimates must fit."""
    d_p = _pad_up(d, _LANE)
    block_k = _pick_block_k(s) if block_k == _BLOCK_K else min(block_k, s)
    block_q = min(block_q, s)
    fwd = (2 * block_k * d_p * itemsize       # K + V blocks
           + block_q * d_p * itemsize         # Q block
           + 2 * block_q * block_k * 4        # scores + probs (f32)
           + block_q * d_p * 4                # O scratch
           + 2 * block_q * _LANE * 4)         # m / l scratch
    bwd = (2 * block_k * d_p * itemsize       # K + V blocks
           + 2 * block_q * d_p * itemsize     # Q + dO blocks
           + 2 * block_q * _LANE * 4          # lse + delta blocks
           + 3 * block_q * block_k * 4        # p / dp / ds (f32)
           + 2 * block_k * d_p * 4)           # dK + dV accumulators
    return max(fwd, bwd) <= PALLAS_IMAGE_VMEM_BUDGET


def _masked_scores(qb, kb, qi, ki, block_q, block_k, scale, causal,
                   kv_valid=None):
    """Score block sc = scale * Q K^T with the causal and/or KV-padding
    mask applied — THE shared definition for the forward and both
    backward kernels, so mask/scale/_NEG_INF semantics cannot
    desynchronize between them.  `kv_valid` (static) masks key columns
    >= the true sequence length when S was padded up to the block grid:
    zero-padded K rows would otherwise score 0 and steal softmax mass
    from every valid query."""
    sc = jax.lax.dot_general(
        qb, kb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale      # [bq, bk]
    if causal or kv_valid is not None:
        cols = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 1)
        mask = None
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, sc.shape, 0)
            mask = (qi * block_q + rows) >= (ki * block_k + cols)
        if kv_valid is not None:
            kv_mask = (ki * block_k + cols) < kv_valid
            mask = kv_mask if mask is None else (mask & kv_mask)
        sc = jnp.where(mask, sc, _NEG_INF)
    return sc


def _dscores(p, dob, vb, dlt, scale):
    """ds = p * (dO V^T - delta) * scale — shared by both backward
    kernels (dp in f32, ds cast at the consuming matmul)."""
    dp = jax.lax.dot_general(
        dob, vb, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)              # [bq, bk]
    return p * (dp - dlt) * scale


@partial(jax.jit, static_argnames=("causal", "scale", "kv_valid"))
def _attention_pallas(q, k, v, causal: bool, scale: float,
                      kv_valid=None):
    """q,k,v: [BH, S, D_padded] (D padded to a lane multiple) -> [BH, S,
    D_padded] f32.  `scale` is 1/sqrt(TRUE head dim) — the padded D must
    not leak into the softmax temperature."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q.shape
    block_q = min(_BLOCK_Q, s)
    block_k = _pick_block_k(s)
    n_k = s // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, o_acc, m_acc, l_acc,
               *, scale):
        ki = pl.program_id(2)
        qi = pl.program_id(1)

        @pl.when(ki == 0)
        def _init():
            o_acc[...] = jnp.zeros_like(o_acc)
            m_acc[...] = jnp.full_like(m_acc, _NEG_INF)
            l_acc[...] = jnp.zeros_like(l_acc)

        # causal: K blocks entirely above the diagonal are pure no-op work
        # (up to ~half the grid at long S) — skip both matmuls for them
        visible = ((qi * block_q + block_q - 1 >= ki * block_k)
                   if causal else (ki >= 0))

        @pl.when(visible)
        def _update():
            qb = q_ref[0]                    # [block_q, D]
            kb = k_ref[0]                    # [block_k, D]
            vb = v_ref[0]
            sc = _masked_scores(qb, kb, qi, ki, block_q, block_k,
                                scale, causal, kv_valid)
            # online softmax: m/l live lane-broadcast in [bq, LANE]
            # scratch.  Read via full-tile load + lane reduction (all
            # lanes hold the same value) — a narrow [:, :1] ref slice is
            # not a safe Mosaic tile access
            m_prev = jnp.max(m_acc[...], axis=-1, keepdims=True)
            l_prev = jnp.max(l_acc[...], axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1, keepdims=True))
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(sc - m_new)                        # [bq, bk] f32
            l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
            o_acc[...] = o_acc[...] * corr + jax.lax.dot_general(
                p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_acc[...] = jnp.broadcast_to(m_new, m_acc.shape)
            l_acc[...] = jnp.broadcast_to(l_new, l_acc.shape)

        @pl.when(ki == n_k - 1)
        def _finish():
            # fully-masked rows (possible only with non-causal all-pad
            # inputs) keep l=0; guard the divide.  Full-tile read + lane
            # reduction again (lanes are equal by construction).
            l_fin = jnp.max(l_acc[...], axis=-1, keepdims=True)
            o_ref[0] = o_acc[...] / jnp.maximum(l_fin, 1e-20)
            # logsumexp residual for the flash backward: rows the causal
            # mask fully hides never update m (=-inf stand-in) — their
            # lse is meaningless and the backward masks them anyway
            m_fin = jnp.max(m_acc[...], axis=-1, keepdims=True)
            lse = m_fin + jnp.log(jnp.maximum(l_fin, 1e-20))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])

    return pl.pallas_call(
        partial(kernel, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, s, _LANE), jnp.float32)),
        grid=(bh, s // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)


def _xla_attention(q, k, v, causal: bool):
    from ..parallel.ring_attention import full_attention

    return full_attention(q, k, v, causal=causal)


@partial(jax.jit, static_argnames=("causal", "scale", "kv_valid"))
def _attention_bwd_dkdv(q, k, v, do, lse, delta, causal: bool, scale: float,
                        kv_valid=None):
    """dK/dV: grid (BH, n_k, n_q) with Q innermost — each (b, k-block)
    streams every visible Q/dO block, recomputing its score block from
    the saved lse (p = exp(s - lse), exact, no renormalization pass),
    accumulating dV += p^T dO and dK += ds^T Q in VMEM.  All inputs are
    [BH, S, D_pad] except lse/delta [BH, S, LANE] lane-broadcast."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q.shape
    block_q = min(_BLOCK_Q, s)
    block_k = _pick_block_k(s)
    n_q = s // block_q

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
               dk_ref, dv_ref, dk_acc, dv_acc, *, scale):
        kj = pl.program_id(1)
        qi = pl.program_id(2)

        @pl.when(qi == 0)
        def _init():
            dk_acc[...] = jnp.zeros_like(dk_acc)
            dv_acc[...] = jnp.zeros_like(dv_acc)

        visible = ((qi * block_q + block_q - 1 >= kj * block_k)
                   if causal else (qi >= 0))

        @pl.when(visible)
        def _update():
            qb = q_ref[0]
            kb = k_ref[0]
            vb = v_ref[0]
            dob = do_ref[0]
            lse = jnp.max(lse_ref[0], axis=-1, keepdims=True)   # [bq, 1]
            dlt = jnp.max(dl_ref[0], axis=-1, keepdims=True)    # [bq, 1]
            sc = _masked_scores(qb, kb, qi, kj, block_q, block_k,
                                scale, causal, kv_valid)
            p = jnp.exp(sc - lse)                                # [bq, bk]
            dv_acc[...] += jax.lax.dot_general(
                p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)              # [bk, D]
            ds = _dscores(p, dob, vb, dlt, scale)
            dk_acc[...] += jax.lax.dot_general(
                ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)              # [bk, D]

        @pl.when(qi == n_q - 1)
        def _finish():
            dk_ref[0] = dk_acc[...]
            dv_ref[0] = dv_acc[...]

    return pl.pallas_call(
        partial(kernel, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, s, d), jnp.float32)),
        grid=(bh, s // block_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)


@partial(jax.jit, static_argnames=("causal", "scale", "kv_valid"))
def _attention_bwd_dq(q, k, v, do, lse, delta, causal: bool, scale: float,
                      kv_valid=None):
    """dQ: grid (BH, n_q, n_k) with K innermost — the forward's layout,
    accumulating dQ += ds @ K across the streamed K/V blocks."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s, d = q.shape
    block_q = min(_BLOCK_Q, s)
    block_k = _pick_block_k(s)
    n_k = s // block_k

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
               dq_ref, dq_acc, *, scale):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            dq_acc[...] = jnp.zeros_like(dq_acc)

        visible = ((qi * block_q + block_q - 1 >= ki * block_k)
                   if causal else (ki >= 0))

        @pl.when(visible)
        def _update():
            qb = q_ref[0]
            kb = k_ref[0]
            vb = v_ref[0]
            dob = do_ref[0]
            lse = jnp.max(lse_ref[0], axis=-1, keepdims=True)
            dlt = jnp.max(dl_ref[0], axis=-1, keepdims=True)
            sc = _masked_scores(qb, kb, qi, ki, block_q, block_k,
                                scale, causal, kv_valid)
            p = jnp.exp(sc - lse)
            ds = _dscores(p, dob, vb, dlt, scale)
            dq_acc[...] += jax.lax.dot_general(
                ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)              # [bq, D]

        @pl.when(ki == n_k - 1)
        def _finish():
            dq_ref[0] = dq_acc[...]

    return pl.pallas_call(
        partial(kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), jnp.float32),
        grid=(bh, s // block_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)


def _padded_len(s: int):
    """Kernel-grid sequence length for s, or None when the kernel should
    decline.  Non-block-multiple lengths (ViT's S=196, ragged text) pad
    up to the 128 grid with `kv_valid` masking — accepted only while the
    padded work stays within 1.5x of the true length, past which the
    masked blocks cost more than XLA dense's score traffic."""
    if s < 8:
        return None
    if s % min(_BLOCK_Q, s) == 0 and s % 8 == 0:
        return s                       # native fit, no padding
    s_p = _pad_up(s, _BLOCK_Q)
    return s_p if 2 * s_p <= 3 * s else None


def kernel_ok(q) -> bool:
    """Public predicate: will fused_attention take the Pallas kernel for
    this (B, S, H, D) array, or fall back to the XLA composition?"""
    b, s, h, d = q.shape
    if not pallas_available():
        return False
    s_p = _padded_len(s)
    if s_p is None:
        return False
    # lane padding below d=64 (4x+ wasted MXU work and padded HBM copies)
    # makes the kernel a net loss vs XLA dense — keep small heads on XLA
    if d < 64:
        return False
    if _sig(s_p, d, q.dtype) in _REJECTED_FWD:
        return False  # this signature's pallas lowering already failed
    return attention_fits_vmem(s_p, d, q.dtype.itemsize)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_attention(q, k, v, causal: bool = True):
    """Drop-in for full_attention: (B, S, H, D) -> (B, S, H, D) f32.

    VMEM-resident scores on TPU via Pallas (interpret mode elsewhere).
    Non-block-multiple S (ViT's 196, ragged text) pads up to the 128
    grid with kv_valid masking while the padded work stays within 1.5x
    of the true length (`_padded_len`); beyond that, and for head dim
    < 64 (lane padding wastes the MXU), the XLA composition runs
    instead — `kernel_ok(q)` is the public predicate.  Scale uses the
    TRUE head dim even when D pads to the 128 lane.  Differentiable:
    kernel-path shapes take the flash backward kernels (blockwise
    recompute from the saved logsumexp — matches the XLA gradients to
    MXU precision, ~1e-3 on bf16 passes); fallback shapes keep the
    exact XLA recompute.
    """
    return _fused_attention_fwd(q, k, v, causal)[0]


def _to_bhsd(x, d_p):
    """[B, S, H, D] -> [B*H, S, D_pad] (the kernels' layout)."""
    b, s, h, d = x.shape
    x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)
    if d_p != d:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, d_p - d)))
    return x


def _from_bhsd(x, b, s, h, d):
    return x[..., :d].reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _pad_seq(x, s_p):
    s = x.shape[1]
    if s_p == s:
        return x
    return jnp.pad(x, ((0, 0), (0, s_p - s), (0, 0)))


_NATIVE_D64_OK = None
# Per-shape self-healing (the process-wide probe runs one tiny shape;
# Mosaic's tiling rules depend on the FULL (S, D, dtype) signature, so a
# passing probe does not clear every production shape).  A pallas_call
# that raises for a signature lands here and never retries:
_REJECTED_NATIVE_D: set = set()   # head dims whose 64-mod native run failed
_REJECTED_FWD: set = set()        # (s_pad, d, dtype) -> XLA composition
_REJECTED_BWD: set = set()        # (s_pad, d_pad, dtype) -> XLA recompute


def _sig(s_p, d, dtype) -> tuple:
    return (int(s_p), int(d), jnp.dtype(dtype).str)


def _native_d64_ok() -> bool:
    """Can the kernels run with a 64-lane head dim natively (no pad to
    128)?  The padded path doubles the QK^T contraction's MAC count with
    zeros AND materializes 2x-size copies of q/k/v/o around every call —
    for d_head=64 models (the LM and ViT-B flagship shapes) that is pure
    waste when Mosaic takes the 64-minor tiles.  Probed ONCE per process
    by compiling all three kernels on a tiny shape in the PRODUCTION
    dtype (bf16 — Mosaic tiling is dtype-dependent: f32 (8, 128) tiles
    passing says nothing about the (16, 128) bf16 tiles the real models
    feed) and checking the forward numerically against the XLA
    composition on RANDOM input (zeros compile-and-run can succeed while
    the lowering is wrong: softmax over an all-zero score row hides any
    normalization or masking bug).  A rejection self-heals to the padded
    path, so this can never cost a bench run; shapes the probe wrongly
    clears still self-heal per-signature via _REJECTED_NATIVE_D."""
    global _NATIVE_D64_OK
    if _NATIVE_D64_OK is None:
        if _interpret():
            _NATIVE_D64_OK = True  # interpret mode has no tiling rules
        else:
            _NATIVE_D64_OK = _probe_native_d64()
    return _NATIVE_D64_OK


def _probe_native_d64() -> bool:
    # deliberate trace-time host work: this probe runs ONCE per process
    # while the first d=64 attention call is being traced, on its own
    # concrete arrays (never tracers) — the host RNG and blocking syncs
    # are the point, not a hazard
    import numpy as _np

    rng = _np.random.default_rng(0)  # graftlint: disable=G103
    try:
        q, k, v, do = (jnp.asarray(rng.standard_normal((1, 128, 64)),
                                   jnp.bfloat16) for _ in range(4))
        st = jnp.zeros((1, 128, _LANE), jnp.float32)
        o, lse = _attention_pallas(q, k, v, True, 0.125, None)
        jax.block_until_ready(  # graftlint: disable=G106
            _attention_bwd_dkdv(q, k, v, do, st, st, True, 0.125, None))
        jax.block_until_ready(  # graftlint: disable=G106
            _attention_bwd_dq(q, k, v, do, st, st, True, 0.125, None))
        # graftlint: disable=G106
        o = _np.asarray(jax.block_until_ready(o))
    except Exception:  # noqa: BLE001 — any compile/run rejection
        return False
    # numerical parity with the XLA composition, same bhsd inputs: the
    # tolerance covers the kernel's one extra rounding (probabilities
    # cast to bf16 at the PV matmul), two orders below a real mask/
    # normalization bug (O(1) error)
    ref = _np.asarray(
        _xla_attention(q[:, :, None, :], k[:, :, None, :],
                       v[:, :, None, :], True))[:, :, 0, :]
    return bool(_np.max(_np.abs(o - ref)) <= 5e-2)


def _kernel_d(d: int) -> int:
    """Head-dim the kernels run at: lane-multiple dims are native; the
    64-mod-128 dims (64, 192, ...) stay native when the probe passes and
    no production shape at this head dim has been rejected; everything
    else pads up to the 128 lane."""
    if d % _LANE == 0:
        return d
    if d % 64 == 0 and d not in _REJECTED_NATIVE_D and _native_d64_ok():
        return d
    return _pad_up(d, _LANE)


def _run_kernel(q, k, v, causal: bool):
    b, s, h, d = q.shape
    d_p = _kernel_d(d)
    s_p = _padded_len(s)
    kv_valid = s if s_p != s else None
    scale = 1.0 / float(d) ** 0.5
    try:
        o, lse = _attention_pallas(
            _pad_seq(_to_bhsd(q, d_p), s_p), _pad_seq(_to_bhsd(k, d_p), s_p),
            _pad_seq(_to_bhsd(v, d_p), s_p), causal, scale, kv_valid)
    except Exception:  # noqa: BLE001 — per-shape Mosaic rejection
        if d_p % _LANE == 0:
            raise  # already lane-padded: nothing gentler to retry
        # the probe cleared 64-mod head dims on a tiny shape alone; THIS
        # signature's lowering was rejected — cache and retry padded (a
        # padded failure escapes to the forward's XLA fallback)
        _REJECTED_NATIVE_D.add(d)
        d_p = _pad_up(d, _LANE)
        o, lse = _attention_pallas(
            _pad_seq(_to_bhsd(q, d_p), s_p), _pad_seq(_to_bhsd(k, d_p), s_p),
            _pad_seq(_to_bhsd(v, d_p), s_p), causal, scale, kv_valid)
    # keep one lane of the broadcast lse as the backward residual
    return _from_bhsd(o[:, :s], b, s, h, d), lse[:, :s, 0]


def _fused_attention_fwd(q, k, v, causal):
    if kernel_ok(q):
        try:
            out, lse = _run_kernel(q, k, v, causal)
            return out, (q, k, v, out, lse)
        except Exception:  # noqa: BLE001 — even padded pallas rejected
            _REJECTED_FWD.add(_sig(_padded_len(q.shape[1]), q.shape[3],
                                   q.dtype))
    # fallback backward recomputes from q/k/v alone — saving `out` here
    # would keep a dead [B, S, H, D] f32 alive until the backward
    return _xla_attention(q, k, v, causal), (q, k, v, None, None)


def _fused_attention_bwd(causal, res, g):
    q, k, v, out, lse = res
    if lse is None:  # forward ran the XLA composition: exact recompute
        _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal),
                         q, k, v)
        return vjp(g)
    b, s, h, d = q.shape
    d_p = _kernel_d(d)  # same decision as _run_kernel (cached probe)
    s_p = _padded_len(s)
    if _sig(s_p, d_p, q.dtype) not in _REJECTED_BWD:
        try:
            return _flash_bwd(q, k, v, out, lse, g, causal, d_p, s_p)
        except Exception:  # noqa: BLE001 — per-shape Mosaic rejection of
            # a backward kernel: cache it and recompute the exact XLA
            # gradients from q/k/v (forward output is discarded)
            _REJECTED_BWD.add(_sig(s_p, d_p, q.dtype))
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal),
                     q, k, v)
    return vjp(g)


def _flash_bwd(q, k, v, out, lse, g, causal, d_p, s_p):
    b, s, h, d = q.shape
    kv_valid = s if s_p != s else None
    scale = 1.0 / float(d) ** 0.5
    # delta = rowsum(dO * O) on the TRUE head dim (pad columns are zero).
    # Padded Q rows are inert by construction: their dO rows pad to zero,
    # so every dv/dk contribution they touch is zero; lse/delta pad 0.
    delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32), out)
    delta = _pad_seq(delta.reshape(b * h, s)[..., None], s_p)
    delta = jnp.broadcast_to(delta, (b * h, s_p, _LANE))
    lse = jnp.broadcast_to(_pad_seq(lse[..., None], s_p),
                           (b * h, s_p, _LANE))
    # matmul-heavy backward runs at the inputs' dtype (bf16 on the MXU)
    # with f32 accumulation, like the forward
    qp, kp, vp = (_pad_seq(_to_bhsd(x, d_p), s_p) for x in (q, k, v))
    dop = _pad_seq(_to_bhsd(g.astype(q.dtype), d_p), s_p)
    dk, dv = _attention_bwd_dkdv(qp, kp, vp, dop, lse, delta, causal,
                                 scale, kv_valid)
    dq = _attention_bwd_dq(qp, kp, vp, dop, lse, delta, causal,
                           scale, kv_valid)
    return (_from_bhsd(dq[:, :s], b, s, h, d).astype(q.dtype),
            _from_bhsd(dk[:, :s], b, s, h, d).astype(k.dtype),
            _from_bhsd(dv[:, :s], b, s, h, d).astype(v.dtype))


fused_attention.defvjp(_fused_attention_fwd, _fused_attention_bwd)
