"""Image pipeline stages: the opencv-module + core/image equivalents.

Reference:
  - ImageTransformer (opencv/.../ImageTransformer.scala:282-400): a list of
    named ops (resize/crop/colorFormat/flip/blur/threshold/gaussianKernel)
    compiled per partition and applied per row via OpenCV Mats.
  - ResizeImageTransformer (core/image/ResizeImageTransformer.scala)
  - UnrollImage / UnrollBinaryImage (core/image/UnrollImage.scala:30-232)
  - ImageSetAugmenter (opencv/.../ImageSetAugmenter.scala)

TPU-first design: instead of per-row Mat calls, the op list is traced once
into a single jitted function over a `[B,H,W,C] float32` batch; rows are
grouped by shape so XLA sees static shapes, and the whole pipeline fuses into
one program per shape group.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.registry import register_stage
from ..core.schema import Table
from ..io.image import array_to_image_row, image_row_to_array, safe_read
from . import image as I

__all__ = [
    "ImageTransformer",
    "ResizeImageTransformer",
    "UnrollImage",
    "UnrollBinaryImage",
    "ImageSetAugmenter",
]


def _rows_to_shape_groups(col: np.ndarray) -> Dict[Tuple[int, int, int], List[int]]:
    groups: Dict[Tuple[int, int, int], List[int]] = {}
    for i, row in enumerate(col):
        arr_shape = (row["height"], row["width"], row["nChannels"])
        groups.setdefault(arr_shape, []).append(i)
    return groups


def _decode_cell(v: Any) -> Optional[Dict[str, Any]]:
    """Accept image rows, raw encoded bytes, or ndarray."""
    if v is None:
        return None
    if isinstance(v, dict):
        return v
    if isinstance(v, (bytes, bytearray)):
        return safe_read(bytes(v))
    if isinstance(v, np.ndarray) and v.ndim >= 2:
        return array_to_image_row(v)
    return None


def decode_cells(col: np.ndarray) -> list:
    """Decode a whole image column (rows/bytes/arrays -> image rows, None
    for undecodable cells).  PIL's and the native decoder's codecs release
    the GIL, so larger columns decode thread-parallel — the shared host
    decode policy of ImageFeaturizer/DeepVisionClassifier (the reference
    decodes per-row on JVM task threads, ImageUtils.scala:26).

    Cells that are already decoded (image-row dicts, ndarray pixels) are
    short-circuited inline BEFORE the pool: only encoded bytes pay a
    codec, and a column of mostly-decoded rows with a few encoded
    stragglers no longer spins up 16 threads to re-wrap ndarrays.  Wall
    time and item count land in the pipeline telemetry's "decode" stage
    so bench.py's per-stage breakdown covers this path too."""
    import os
    import time

    from ..core import telemetry as core_telemetry
    from ..io.pipeline import PIPELINE_TELEMETRY

    out: list = [None] * len(col)
    pending: list = []  # indices still needing a codec (bytes/unknown)
    for i, v in enumerate(col):
        if v is None:
            continue
        if isinstance(v, dict):
            out[i] = v
        elif isinstance(v, np.ndarray) and v.ndim >= 2:
            out[i] = array_to_image_row(v)
        else:
            pending.append(i)
    if not pending:
        return out
    t0 = time.perf_counter()
    if len(pending) > 32:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
                max_workers=min(16, os.cpu_count() or 4)) as ex:
            rows = list(ex.map(_decode_cell, (col[i] for i in pending)))
    else:
        rows = [_decode_cell(col[i]) for i in pending]
    for i, row in zip(pending, rows):
        out[i] = row
    dt = time.perf_counter() - t0
    PIPELINE_TELEMETRY.add("decode", busy_s=dt, items=len(pending))
    core_telemetry.histogram("io.pipeline.stage.latency",
                             stage="decode").observe(dt)
    return out


class _BatchedImageStage(Transformer):
    """Shared machinery: gather image rows -> same-shape float32 batches ->
    jitted op pipeline -> scatter back."""

    input_col = Param("image column", default="image")
    output_col = Param("output column", default=None)

    def _pipeline_fn(self) -> Callable[[jnp.ndarray], jnp.ndarray]:
        raise NotImplementedError

    def _float_output(self) -> bool:
        """When True, emit float arrays instead of uint8 image rows (e.g. a
        pipeline ending in normalize would be destroyed by uint8 clipping)."""
        return False

    def _emit(self, out_batch: np.ndarray, src_rows: List[dict]) -> List[Any]:
        if self._float_output():
            return [np.asarray(a, dtype=np.float32) for a in out_batch]
        return [
            array_to_image_row(np.clip(a, 0, 255).astype(np.uint8),
                               origin=r.get("origin", ""))
            for a, r in zip(out_batch, src_rows)
        ]

    def _run_group(self, batch: np.ndarray) -> np.ndarray:
        """One same-shape float32 batch -> output batch.  Base: the jitted
        op-list composition, cached per stage instance AND current param
        values — a param mutation after a transform invalidates the cache
        (jit re-specializes per input shape as usual)."""
        token = repr(sorted(self.simple_param_values().items()))
        cached = self.__dict__.get("_jitted_pipeline")
        if cached is None or cached[0] != token:
            from ..core import telemetry as core_telemetry
            cached = (token, core_telemetry.watch_compiles(
                jax.jit(self._pipeline_fn()),
                name=f"image_stages.{type(self).__name__}"))
            self.__dict__["_jitted_pipeline"] = cached
        return np.asarray(cached[1](jnp.asarray(batch)))

    def _transform(self, table: Table) -> Table:
        out_col = self.output_col or self.input_col
        cells = [_decode_cell(v) for v in table[self.input_col]]
        result: List[Any] = [None] * table.num_rows
        valid_idx = [i for i, c in enumerate(cells) if c is not None]
        valid = np.empty(len(valid_idx), dtype=object)
        for j, i in enumerate(valid_idx):
            valid[j] = cells[i]
        for _shape, members in _rows_to_shape_groups(valid).items():
            rows = [valid[m] for m in members]
            batch = np.stack([image_row_to_array(r) for r in rows]).astype(np.float32)
            out = self._run_group(batch)
            for r_out, m in zip(self._emit(out, rows), members):
                result[valid_idx[m]] = r_out
        return table.with_column(out_col, result)


@register_stage
class ImageTransformer(_BatchedImageStage):
    """Op-list image preprocessing — the OpenCV ImageTransformer equivalent
    (ImageTransformer.scala:282-400).  Ops are (name, kwargs) pairs added
    fluently; the list compiles to ONE fused XLA program.
    """

    stages = Param("list of [op_name, kwargs] pairs", default=None)
    fuse = Param(
        "fold the whole op list into ONE two-matmul Pallas pass when every "
        "op is separable-linear (crop/resize/flip/blur/color/normalize): "
        "None = auto (real TPU only), False = always the XLA composition",
        default=None)

    _OPS = {
        "resize": lambda b, height, width, method="linear": I.resize(b, height, width, method),
        "crop": lambda b, x, y, width, height: I.crop(b, x, y, width, height),
        "centerCrop": lambda b, height, width: I.center_crop(b, height, width),
        "colorFormat": lambda b, format: I.color_convert(b, format),
        "flip": lambda b, flipLeftRight=True, flipUpDown=False: I.flip(b, flipLeftRight, flipUpDown),
        "blur": lambda b, height, width: I.box_blur(b, int(height), int(width)),
        "gaussianKernel": lambda b, apertureSize, sigma: I.gaussian_blur(b, int(apertureSize), sigma),
        "threshold": lambda b, threshold, maxVal, thresholdType="binary": I.threshold(
            b, threshold, maxVal, thresholdType),
        "normalize": lambda b, mean, std, scale=1.0: I.normalize(b, mean, std, scale),
    }

    # ---- fluent builders (mirroring the reference's setter API) -------
    def _add(self, name: str, **kwargs) -> "ImageTransformer":
        ops = list(self.stages or [])
        ops.append([name, kwargs])
        self.set(stages=ops)
        return self

    def resize(self, height: int, width: int, method: str = "linear"):
        return self._add("resize", height=height, width=width, method=method)

    def crop(self, x: int, y: int, width: int, height: int):
        return self._add("crop", x=x, y=y, width=width, height=height)

    def center_crop(self, height: int, width: int):
        return self._add("centerCrop", height=height, width=width)

    def color_format(self, format: str):
        return self._add("colorFormat", format=format)

    def flip(self, flip_left_right: bool = True, flip_up_down: bool = False):
        return self._add("flip", flipLeftRight=flip_left_right, flipUpDown=flip_up_down)

    def blur(self, height: float, width: float):
        return self._add("blur", height=height, width=width)

    def gaussian_kernel(self, aperture_size: int, sigma: float):
        return self._add("gaussianKernel", apertureSize=aperture_size, sigma=sigma)

    def threshold(self, threshold: float, max_val: float, threshold_type: str = "binary"):
        return self._add("threshold", threshold=threshold, maxVal=max_val,
                         thresholdType=threshold_type)

    def normalize(self, mean, std, scale: float = 1.0):
        return self._add("normalize", mean=mean, std=std, scale=scale)

    def _float_output(self) -> bool:
        # a normalize (or sub-1 threshold) tail produces float-scale values;
        # clipping those to uint8 would zero them out
        for name, kwargs in self.stages or []:
            if name == "normalize":
                return True
            if name == "threshold" and kwargs.get("maxVal", 255) <= 1.0:
                return True
        return False

    def _pipeline_fn(self):
        ops = [(self._OPS[name], dict(kwargs)) for name, kwargs in (self.stages or [])]

        def run(batch):
            for fn, kwargs in ops:
                batch = fn(batch, **kwargs)
            return batch

        return run

    def _fuse_wanted(self) -> bool:
        from .pallas_kernels import pallas_available

        f = self.get_or_default("fuse")
        if f is False or not pallas_available():
            return False
        if f is None:  # auto: interpret-mode Pallas on CPU is slower than XLA
            return jax.default_backend() == "tpu"
        return True

    def _run_group(self, batch: np.ndarray) -> np.ndarray:
        if self._fuse_wanted():
            from .pallas_kernels import (
                affine_plan, freeze_stages, fused_affine_apply)

            plan = affine_plan(freeze_stages(self.stages),
                               *batch.shape[1:],
                               itemsize=batch.dtype.itemsize)
            if plan is not None:
                return np.asarray(fused_affine_apply(jnp.asarray(batch),
                                                     plan))
        return super()._run_group(batch)


@register_stage
class ResizeImageTransformer(_BatchedImageStage):
    """Resize-only stage (core/image/ResizeImageTransformer.scala)."""

    height = Param("target height", converter=TypeConverters.to_int)
    width = Param("target width", converter=TypeConverters.to_int)
    method = Param("linear|nearest|cubic", default="linear")

    def _pipeline_fn(self):
        h, w, m = self.height, self.width, self.method
        return lambda b: I.resize(b, h, w, m)


@register_stage
class UnrollImage(_BatchedImageStage):
    """Image rows -> flat CHW float vector column
    (core/image/UnrollImage.scala:30-55: unsigned-byte fix + c*h*w layout).

    The unroll (+ optional per-channel normalize) runs as the fused Pallas
    kernel (ops/pallas_kernels.py) — one HBM round-trip per image."""

    input_col = Param("image column", default="image")
    output_col = Param("vector column", default="unrolled")
    mean = Param("per-channel mean to subtract", default=None,
                 converter=TypeConverters.to_list_float)
    std = Param("per-channel std to divide", default=None,
                converter=TypeConverters.to_list_float)

    def _pipeline_fn(self):
        from .pallas_kernels import fused_normalize_unroll

        mean = self.get_or_default("mean") or (0.0,)
        std = self.get_or_default("std") or (1.0,)
        return lambda batch: fused_normalize_unroll(batch, mean, std)

    def _emit(self, out_batch, src_rows):
        return [np.asarray(v, dtype=np.float64) for v in out_batch]


@register_stage
class UnrollBinaryImage(_BatchedImageStage):
    """Raw encoded bytes -> (optional resize) -> flat CHW vector
    (UnrollImage.scala:161-232, UnrollBinaryImage)."""

    input_col = Param("binary column", default="bytes")
    output_col = Param("vector column", default="unrolled")
    height = Param("optional resize height", default=None)
    width = Param("optional resize width", default=None)

    def _pipeline_fn(self):
        h, w = self.height, self.width

        def run(batch):
            if h is not None and w is not None:
                batch = I.resize(batch, int(h), int(w))
            return I.hwc_to_chw_flat(batch)

        return run

    def _emit(self, out_batch, src_rows):
        return [np.asarray(v, dtype=np.float64) for v in out_batch]


@register_stage
class ImageSetAugmenter(Transformer):
    """Train-time augmentation: emit original + flipped copies
    (opencv/.../ImageSetAugmenter.scala:77)."""

    input_col = Param("image column", default="image")
    output_col = Param("output column", default="image")
    flip_left_right = Param("emit LR-flipped copy", default=True,
                            converter=TypeConverters.to_bool)
    flip_up_down = Param("emit UD-flipped copy", default=False,
                         converter=TypeConverters.to_bool)

    def _transform(self, table: Table) -> Table:
        parts = [table.with_column(self.output_col, table[self.input_col])]
        flips = []
        if self.flip_left_right:
            flips.append((True, False))
        if self.flip_up_down:
            flips.append((False, True))
        for lr, ud in flips:
            t = ImageTransformer(input_col=self.input_col, output_col=self.output_col)
            t.flip(flip_left_right=lr, flip_up_down=ud)
            parts.append(t.transform(table))
        return Table.concat(parts)
