"""Int8 post-training-quantized inference ops.

Beyond-reference, TPU-first: a v5e's MXU runs int8 matmuls at ~2x its bf16
FLOP rate (394 TOPS vs 197 TFLOP/s), so inference-heavy paths (the
reference's CNTKModel scoring role, CNTKModel.scala:88-140) can trade a
little precision for double math throughput with NO retraining and NO
separate checkpoint format:

- `QuantDense` keeps the exact param pytree of `nn.Dense` (f32 kernel/bias)
  — any trained checkpoint loads unchanged; quantization happens inside the
  forward, on device.
- Weights: symmetric per-output-channel int8 (max|w|/127 scales).
- Activations: dynamic symmetric per-tensor int8, computed per call.
- The matmul itself runs int8 x int8 -> int32 on the MXU
  (`preferred_element_type=int32`), then dequantizes with one fused
  elementwise scale.

Numerics: symmetric scaling bounds |q| <= 127 by construction, so the int8
casts cannot overflow; int32 accumulation is exact for any k <= ~2^16
(127*127*k < 2^31), far past any layer width here.

Weights re-quantize inside each forward by default (they are jit
arguments, so XLA cannot fold them): the extra cost is one f32 kernel
read + elementwise round/cast per call — for ViT-B at batch 128 that is
~344MB against a ~23ms step, ~2% overhead, which keeping the checkpoint
format unchanged buys.  Where that traffic dominates — batch-1
autoregressive decode is weight-bandwidth-bound — `prequantize()` runs
one forward with the 'quant' collection mutable and stores each layer's
(int8 kernel, scales) beside the f32 params; subsequent applies read
int8 weights only (4x less HBM than f32, 2x less than bf16).  Run it
AFTER loading final weights: the cached int8 copy does not track later
param edits.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["int8_dense", "int8_matmul", "quantize_weight", "QuantDense",
           "prequantize"]

_EPS = 1e-8


def quantize_weight(kernel: jnp.ndarray):
    """f32 `[K, N]` -> (int8 `[K, N]`, per-out-channel f32 scales `[N]`)."""
    kernel = kernel.astype(jnp.float32)
    ws = jnp.maximum(jnp.max(jnp.abs(kernel), axis=0), _EPS) / 127.0
    wq = jnp.round(kernel / ws).astype(jnp.int8)
    return wq, ws


def int8_matmul(x: jnp.ndarray, wq: jnp.ndarray, ws: jnp.ndarray,
                bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """`x [..., K]` against a prequantized (wq, ws) weight; returns f32.
    Activation scale is per-tensor dynamic (one max-reduce — cheap next
    to the matmul)."""
    x = x.astype(jnp.float32)
    xs = jnp.maximum(jnp.max(jnp.abs(x)), _EPS) / 127.0  # scalar, dynamic
    xq = jnp.round(x / xs).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (xs * ws)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y


def int8_dense(x: jnp.ndarray, kernel: jnp.ndarray,
               bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """f32/bf16 `x [..., K] @ kernel [K, N]` executed as int8 on the MXU,
    quantizing the weight on the fly."""
    wq, ws = quantize_weight(kernel)
    return int8_matmul(x, wq, ws, bias)


class QuantDense(nn.Module):
    """Drop-in for `nn.Dense` with int8 compute.

    Same constructor surface and the same parameter names/shapes/dtypes
    (f32 'kernel' [K, N], optional 'bias' [N]) — swapping module classes
    re-uses trained weights as-is.  `dtype` is the OUTPUT dtype (matching
    nn.Dense's compute-dtype contract closely enough for the pre-LN
    transformer blocks here, whose next op casts anyway)."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), jnp.float32)
                if self.use_bias else None)
        if self.has_variable("quant", "wq"):
            # prequantized weights (prequantize()): int8 reads only
            y = int8_matmul(x, self.get_variable("quant", "wq"),
                            self.get_variable("quant", "ws"), bias)
        elif not self.is_initializing() and self.is_mutable_collection("quant"):
            # the prequant pass itself: compute once, store beside params.
            # (never during init — a 'quant' snapshot of random init
            # weights would go stale the moment trained params land)
            wq, ws = quantize_weight(kernel)
            self.put_variable("quant", "wq", wq)
            self.put_variable("quant", "ws", ws)
            y = int8_matmul(x, wq, ws, bias)
        else:
            y = int8_dense(x, kernel, bias)
        return y.astype(self.dtype)


def quantize_kv_row(x: jnp.ndarray):
    """[..., H, D] K/V rows -> (int8 rows, f32 per-row-per-head scales
    [..., H]).  Symmetric per-(position, head) scaling: each attention
    row dequantizes exactly like int8_matmul's weights do."""
    s = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1),
                    _EPS) / 127.0
    q = jnp.round(x.astype(jnp.float32) / s[..., None]).astype(jnp.int8)
    return q, s


def dense_cls(quant: bool):
    """The one quant -> dense-class selection both model families use."""
    return QuantDense if quant else nn.Dense


def prequantize(model: nn.Module, variables: dict, sample_input,
                **apply_kwargs) -> dict:
    """One forward with the 'quant' collection mutable: every QuantDense
    stores its (int8 kernel, scales), and the returned variables dict
    carries them beside the unchanged f32 params.  Call AFTER final
    weights are loaded; re-call after any param update."""
    # strip any existing quant collection: QuantDense prefers stored int8
    # weights, so leaving it in would re-emit the stale copy verbatim
    fresh = {c: v for c, v in variables.items() if c != "quant"}
    _, mutated = model.apply(fresh, sample_input,
                             mutable=["quant"], **apply_kwargs)
    if "quant" not in mutated:
        raise ValueError(
            "prequantize: the model has no QuantDense layers — build it "
            "with quant=True (vit_*/transformer_lm) or use QuantDense "
            "directly")
    return {**fresh, "quant": mutated["quant"]}
