"""Int8 post-training-quantized inference ops.

Beyond-reference, TPU-first: a v5e's MXU runs int8 matmuls at ~2x its bf16
FLOP rate (394 TOPS vs 197 TFLOP/s), so inference-heavy paths (the
reference's CNTKModel scoring role, CNTKModel.scala:88-140) can trade a
little precision for double math throughput with NO retraining and NO
separate checkpoint format:

- `QuantDense` keeps the exact param pytree of `nn.Dense` (f32 kernel/bias)
  — any trained checkpoint loads unchanged; quantization happens inside the
  forward, on device.
- Weights: symmetric per-output-channel int8 (max|w|/127 scales).
- Activations: dynamic symmetric per-tensor int8, computed per call.
- The matmul itself runs int8 x int8 -> int32 on the MXU
  (`preferred_element_type=int32`), then dequantizes with one fused
  elementwise scale.

Numerics: symmetric scaling bounds |q| <= 127 by construction, so the int8
casts cannot overflow; int32 accumulation is exact for any k <= ~2^16
(127*127*k < 2^31), far past any layer width here.

Weights re-quantize inside each forward (they are jit arguments, so XLA
cannot fold them): the extra cost is one f32 kernel read + elementwise
round/cast per call — for ViT-B at batch 128 that is ~344MB against a
~23ms step, ~2% overhead, which keeping the checkpoint format unchanged
buys.  Small-batch serving loops that want it back should add a
load-time prequant pass (int8 kernels + scale arrays as the variables)
— the planned follow-up, not done here.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["int8_dense", "QuantDense"]

_EPS = 1e-8


def int8_dense(x: jnp.ndarray, kernel: jnp.ndarray,
               bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """f32/bf16 `x [..., K] @ kernel [K, N]` executed as int8 on the MXU.

    Returns f32.  Weight scales are per-output-channel, activation scale is
    per-tensor dynamic (one max-reduce — cheap next to the matmul)."""
    x = x.astype(jnp.float32)
    kernel = kernel.astype(jnp.float32)
    ws = jnp.maximum(jnp.max(jnp.abs(kernel), axis=0), _EPS) / 127.0  # [N]
    wq = jnp.round(kernel / ws).astype(jnp.int8)
    xs = jnp.maximum(jnp.max(jnp.abs(x)), _EPS) / 127.0  # scalar, dynamic
    xq = jnp.round(x / xs).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * (xs * ws)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y


class QuantDense(nn.Module):
    """Drop-in for `nn.Dense` with int8 compute.

    Same constructor surface and the same parameter names/shapes/dtypes
    (f32 'kernel' [K, N], optional 'bias' [N]) — swapping module classes
    re-uses trained weights as-is.  `dtype` is the OUTPUT dtype (matching
    nn.Dense's compute-dtype contract closely enough for the pre-LN
    transformer blocks here, whose next op casts anyway)."""

    features: int
    use_bias: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (x.shape[-1], self.features), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros,
                           (self.features,), jnp.float32)
                if self.use_bias else None)
        return int8_dense(x, kernel, bias).astype(self.dtype)
