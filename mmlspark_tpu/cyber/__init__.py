"""CyberML: collaborative-filtering access-anomaly detection + feature prep.

Reference: core/src/main/python/mmlspark/cyber/ (~1.7k LoC Py:
anomaly/collaborative_filtering.py AccessAnomaly, complement_access.py,
feature/ partitioned scalers and indexers).
"""
from .dataset import DataFactory
from .access_anomaly import (
    AccessAnomaly,
    AccessAnomalyModel,
    ComplementAccessTransformer,
)
from .feature import (
    IdIndexer,
    IdIndexerModel,
    PartitionedMinMaxScaler,
    PartitionedScalerModel,
    PartitionedStandardScaler,
)

__all__ = [
    "DataFactory",
    "AccessAnomaly",
    "AccessAnomalyModel",
    "ComplementAccessTransformer",
    "IdIndexer",
    "IdIndexerModel",
    "PartitionedStandardScaler",
    "PartitionedMinMaxScaler",
    "PartitionedScalerModel",
]
