"""Synthetic access-log factory for CyberML workloads.

Reference: core/src/main/python/mmlspark/cyber/dataset.py:11-163
(DataFactory) — three departments (hr/fin/eng) whose users access their
own department's resources plus a shared join resource, with generators
for clustered TRAINING data (in-department edges), INTRA-department test
data (new in-department pairs — should score normal), and
INTER-department test data (cross-department pairs — should score
anomalous).  The reference's AccessAnomaly tests are built on exactly
these three splits; tests/test_cyber.py mirrors that shape here.

Emits columnar Tables (user/res/likelihood) ready for IdIndexer +
AccessAnomaly instead of pandas DataFrames.
"""
from __future__ import annotations

import itertools
import random
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core.schema import Table

__all__ = ["DataFactory"]


class DataFactory:
    def __init__(self, num_hr_users: int = 7, num_hr_resources: int = 30,
                 num_fin_users: int = 5, num_fin_resources: int = 25,
                 num_eng_users: int = 10, num_eng_resources: int = 50,
                 single_component: bool = True, seed: int = 42):
        self.hr_users = [f"hr_user_{i}" for i in range(num_hr_users)]
        self.hr_resources = [f"hr_res_{i}" for i in range(num_hr_resources)]
        self.fin_users = [f"fin_user_{i}" for i in range(num_fin_users)]
        self.fin_resources = [f"fin_res_{i}"
                              for i in range(num_fin_resources)]
        self.eng_users = [f"eng_user_{i}" for i in range(num_eng_users)]
        self.eng_resources = [f"eng_res_{i}"
                              for i in range(num_eng_resources)]
        # one resource everyone touches keeps the access graph a single
        # connected component (the reference's 'ffa' join resource)
        self.join_resources = ["ffa"] if single_component else []
        self.rand = random.Random(seed)

    def _table(self, tups: List[Tuple[str, str, float]]) -> Table:
        return Table({
            "user_id": np.asarray([t[0] for t in tups], object),
            "res_id": np.asarray([t[1] for t in tups], object),
            "likelihood": np.asarray([float(t[2]) for t in tups],
                                     np.float64),
        })

    def edges_between(self, users: Sequence[str], resources: Sequence[str],
                      ratio: float, full_node_coverage: bool,
                      not_set: Optional[Set[Tuple[str, str]]] = None,
                      ) -> List[Tuple[str, str, float]]:
        """Sample distinct (user, resource, weight) edges covering `ratio`
        of the bipartite graph; `full_node_coverage` keeps sampling until
        every node has at least one edge; `not_set` excludes pairs (so a
        test split never repeats a training pair)."""
        if not users or not resources:
            return []
        required = len(users) * len(resources) * ratio
        tups: List[Tuple[str, str, float]] = []
        seen: Set[Tuple[int, int]] = set()
        seen_u: Set[int] = set()
        seen_r: Set[int] = set()
        # dense ratios pre-materialize the pair universe (same
        # optimization as the reference :75); the sparse path caps its
        # rejection-sampling attempts — a not_set covering the whole
        # graph must return what exists, not spin forever
        cart = (list(itertools.product(range(len(users)),
                                       range(len(resources))))
                if ratio >= 0.5 else None)
        attempts_left = 50 * len(users) * len(resources)
        while (len(tups) < required
               or (full_node_coverage and (len(seen_u) < len(users)
                                           or len(seen_r) < len(resources)))):
            if cart is not None:
                if not cart:
                    break
                ii = self.rand.randint(0, len(cart) - 1)
                ui, ri = cart[ii]
                cart[ii] = cart[-1]
                cart.pop()
            else:
                attempts_left -= 1
                if attempts_left < 0:
                    break
                ui = self.rand.randint(0, len(users) - 1)
                ri = self.rand.randint(0, len(resources) - 1)
            pair = (users[ui], resources[ri])
            if (ui, ri) in seen or (not_set is not None and pair in not_set):
                continue
            seen.add((ui, ri))
            seen_u.add(ui)
            seen_r.add(ri)
            tups.append((*pair, float(self.rand.randint(500, 1000))))
        return tups

    def create_clustered_training_data(self, ratio: float = 0.25) -> Table:
        return self._table(
            self.edges_between(self.hr_users, self.join_resources, 1.0, True)
            + self.edges_between(self.fin_users, self.join_resources, 1.0,
                                 True)
            + self.edges_between(self.eng_users, self.join_resources, 1.0,
                                 True)
            + self.edges_between(self.hr_users, self.hr_resources, ratio,
                                 True)
            + self.edges_between(self.fin_users, self.fin_resources, ratio,
                                 True)
            + self.edges_between(self.eng_users, self.eng_resources, ratio,
                                 True))

    def create_clustered_intra_test_data(self,
                                         train: Optional[Table] = None
                                         ) -> Table:
        """NEW in-department pairs (never in `train`) — the should-score-
        normal split."""
        not_set = (set(zip(train["user_id"], train["res_id"]))
                   if train is not None else None)
        return self._table(
            self.edges_between(self.hr_users, self.hr_resources, 0.025,
                               False, not_set)
            + self.edges_between(self.fin_users, self.fin_resources, 0.05,
                                 False, not_set)
            + self.edges_between(self.eng_users, self.eng_resources, 0.035,
                                 False, not_set))

    def create_clustered_inter_test_data(self) -> Table:
        """Cross-department pairs — the should-score-anomalous split."""
        return self._table(
            self.edges_between(self.hr_users, self.fin_resources, 0.025,
                               False)
            + self.edges_between(self.hr_users, self.eng_resources, 0.025,
                                 False)
            + self.edges_between(self.fin_users, self.hr_resources, 0.05,
                                 False)
            + self.edges_between(self.fin_users, self.eng_resources, 0.05,
                                 False)
            + self.edges_between(self.eng_users, self.fin_resources, 0.035,
                                 False)
            + self.edges_between(self.eng_users, self.hr_resources, 0.035,
                                 False))
