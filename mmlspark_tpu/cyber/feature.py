"""CyberML feature utilities: per-partition indexers and scalers.

Reference: core python mmlspark/cyber/feature/*.py (~400 LoC) — IdIndexer
(string ids -> per-tenant contiguous ints) and partitioned standard/min-max
scalers (statistics computed independently per partition key, e.g. tenant).
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model
from ..core.registry import register_stage
from ..core.schema import Table

__all__ = [
    "IdIndexer",
    "IdIndexerModel",
    "PartitionedStandardScaler",
    "PartitionedMinMaxScaler",
    "PartitionedScalerModel",
]


@register_stage
class IdIndexer(Estimator):
    """Per-tenant contiguous indexing of string ids."""

    input_col = Param("raw id column", default="user")
    partition_key = Param("tenant column ('' = global)", default="")
    output_col = Param("indexed output column", default="indexed")

    def _fit(self, table: Table) -> "IdIndexerModel":
        keys = (
            table[self.partition_key]
            if self.partition_key and self.partition_key in table
            else np.zeros(len(table), np.int64)
        )
        vocab: Dict = {}
        for k, v in zip(keys, table[self.input_col]):
            vocab.setdefault(k, {}).setdefault(str(v), len(vocab.get(k, {})))
        return IdIndexerModel(
            vocab=vocab, input_col=self.input_col,
            partition_key=self.partition_key, output_col=self.output_col,
        )


@register_stage
class IdIndexerModel(Model):
    input_col = Param("raw id column", default="user")
    partition_key = Param("tenant column", default="")
    output_col = Param("indexed output column", default="indexed")
    vocab = ComplexParam("per-partition vocab dict")

    def _transform(self, table: Table) -> Table:
        keys = (
            table[self.partition_key]
            if self.partition_key and self.partition_key in table
            else np.zeros(len(table), np.int64)
        )
        vocab = self.vocab
        out = np.full(len(table), -1, np.int64)
        for i, (k, v) in enumerate(zip(keys, table[self.input_col])):
            out[i] = vocab.get(k, {}).get(str(v), -1)
        return table.with_column(self.output_col, out)

    def partition_size(self, key) -> int:
        return len(self.vocab.get(key, {}))


class _PartitionedScalerBase(Estimator):
    input_col = Param("value column", default="value")
    partition_key = Param("tenant column ('' = global)", default="")
    output_col = Param("scaled output column", default="scaled")

    def _keys(self, table: Table) -> np.ndarray:
        if self.partition_key and self.partition_key in table:
            return np.asarray(table[self.partition_key])
        return np.zeros(len(table), np.int64)

    def _stats(self, vals: np.ndarray) -> Tuple[float, float]:
        raise NotImplementedError

    def _fit(self, table: Table) -> "PartitionedScalerModel":
        keys = self._keys(table)
        vals = np.asarray(table[self.input_col], np.float64)
        stats = {}
        for k in np.unique(keys):
            stats[k] = self._stats(vals[keys == k])
        return PartitionedScalerModel(
            stats=stats, input_col=self.input_col,
            partition_key=self.partition_key, output_col=self.output_col,
        )


@register_stage
class PartitionedStandardScaler(_PartitionedScalerBase):
    """(x - mean) / std per partition."""

    def _stats(self, vals):
        return float(vals.mean()), float(vals.std() + 1e-12)


@register_stage
class PartitionedMinMaxScaler(_PartitionedScalerBase):
    """(x - min) / (max - min) per partition."""

    def _stats(self, vals):
        lo, hi = float(vals.min()), float(vals.max())
        return lo, max(hi - lo, 1e-12)


@register_stage
class PartitionedScalerModel(Model):
    input_col = Param("value column", default="value")
    partition_key = Param("tenant column", default="")
    output_col = Param("scaled output column", default="scaled")
    stats = ComplexParam("per-partition (shift, scale)")

    def _transform(self, table: Table) -> Table:
        keys = (
            np.asarray(table[self.partition_key])
            if self.partition_key and self.partition_key in table
            else np.zeros(len(table), np.int64)
        )
        vals = np.asarray(table[self.input_col], np.float64)
        out = np.zeros(len(table), np.float64)
        stats = self.stats
        for k in np.unique(keys):  # one vectorized op per partition
            shift, scale = stats.get(k, (0.0, 1.0))
            mask = keys == k
            out[mask] = (vals[mask] - shift) / scale
        return table.with_column(self.output_col, out)
