"""AccessAnomaly: collaborative-filtering anomalous-access detection.

Reference: core python mmlspark/cyber/anomaly/collaborative_filtering.py
(988 LoC) — per-tenant ALS user/resource embeddings fit on observed access
(plus sampled complement pairs with zero affinity), scored as the
standardized NEGATIVE predicted affinity: high score = the model did not
expect this (user, resource) access.

TPU redesign: the per-tenant ALS normal-equation solves run as vmapped
batched solves on device (every user factor in one call, every resource
factor in one call) instead of Spark ALS.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..core.registry import register_stage
from ..core.schema import Table

__all__ = ["AccessAnomaly", "AccessAnomalyModel",
           "ComplementAccessTransformer"]


@register_stage
class ComplementAccessTransformer(Transformer):
    """Sample (user, res) pairs NOT present in the access table.

    Reference: cyber/anomaly/complement_access.py (148 LoC) — emits
    `complement_ratio` x len(table) unseen pairs per tenant.
    """

    tenant_col = Param("tenant column ('' = single tenant)", default="")
    user_col = Param("indexed user column", default="user")
    res_col = Param("indexed resource column", default="res")
    complement_ratio = Param("complement rows per observed row", default=1.0,
                             converter=TypeConverters.to_float)
    seed = Param("sampling seed", default=0, converter=TypeConverters.to_int)

    def _transform(self, table: Table) -> Table:
        rng = np.random.default_rng(int(self.seed))
        tenants = (
            np.asarray(table[self.tenant_col])
            if self.tenant_col and self.tenant_col in table
            else np.zeros(len(table), np.int64)
        )
        users = np.asarray(table[self.user_col], np.int64)
        ress = np.asarray(table[self.res_col], np.int64)
        out_t, out_u, out_r = [], [], []
        for t in np.unique(tenants):
            m = tenants == t
            seen = set(zip(users[m].tolist(), ress[m].tolist()))
            n_users = users[m].max() + 1
            n_res = ress[m].max() + 1
            want = int(m.sum() * float(self.complement_ratio))
            grid = n_users * n_res
            budget = grid - len(seen)
            want = min(want, max(budget, 0))
            # dense access matrices defeat rejection sampling; enumerate the
            # complement exactly when unseen pairs are scarce
            if budget <= 4 * want or budget < 0.05 * grid:
                all_keys = np.arange(grid, dtype=np.int64)
                seen_keys = np.fromiter(
                    (u * n_res + r for u, r in seen), np.int64, len(seen)
                )
                unseen = np.setdiff1d(all_keys, seen_keys,
                                      assume_unique=False)
                pick = rng.choice(len(unseen), size=want, replace=False)
                for key in unseen[pick]:
                    out_t.append(t)
                    out_u.append(int(key // n_res))
                    out_r.append(int(key % n_res))
            else:
                got = 0
                attempts = 0
                while got < want and attempts < 50 * max(want, 1):
                    u = int(rng.integers(n_users))
                    r = int(rng.integers(n_res))
                    attempts += 1
                    if (u, r) not in seen:
                        seen.add((u, r))
                        out_t.append(t)
                        out_u.append(u)
                        out_r.append(r)
                        got += 1
        data = {
            self.user_col: np.asarray(out_u, np.int64),
            self.res_col: np.asarray(out_r, np.int64),
        }
        if self.tenant_col:
            data[self.tenant_col] = np.asarray(out_t)
        return Table(data)


@partial(jax.jit, static_argnames=("rank", "n_rows"))
def _als_step_sparse(Y, row_idx, col_idx, vals, c0, l2, rank: int,
                     n_rows: int):
    """Sparse weighted ALS sweep (Hu-Koren construction).

    Solves every row factor given column factors Y with weights:
    1 on observed (row_idx, col_idx) entries, c0 on everything else, and
    target values `vals` on observed entries (0 elsewhere).  Memory is
    O(nnz * rank^2 + n_rows * rank^2) — no dense (rows x cols) matrix.

    A_u = c0 * YᵀY + (1 - c0) * Σ_obs y_r y_rᵀ + l2 I
    b_u = Σ_obs a_ur y_r
    """
    G = Y.T @ Y  # (rank, rank) shared gram
    y_obs = Y[col_idx]  # (nnz, rank)
    outer = jnp.einsum("ni,nj->nij", y_obs, y_obs)
    A_obs = jax.ops.segment_sum(outer, row_idx, num_segments=n_rows)
    b = jax.ops.segment_sum(y_obs * vals[:, None], row_idx,
                            num_segments=n_rows)
    A = c0 * G[None] + (1.0 - c0) * A_obs + l2 * jnp.eye(rank, dtype=Y.dtype)
    return jnp.linalg.solve(A, b[..., None])[..., 0]


@register_stage
class AccessAnomaly(Estimator):
    tenant_col = Param("tenant column ('' = single tenant)", default="")
    user_col = Param("indexed user column", default="user")
    res_col = Param("indexed resource column", default="res")
    likelihood_col = Param("optional access-count column", default="")
    output_col = Param("anomaly score column", default="anomaly_score")
    rank = Param("embedding rank", default=8, converter=TypeConverters.to_int)
    max_iter = Param("ALS sweeps", default=10, converter=TypeConverters.to_int)
    reg_param = Param("ALS l2", default=0.1, converter=TypeConverters.to_float)
    complement_ratio = Param("zero-affinity complement rows per observed row",
                             default=1.0, converter=TypeConverters.to_float)
    seed = Param("seed", default=0, converter=TypeConverters.to_int)

    def _fit(self, table: Table) -> "AccessAnomalyModel":
        tenants = (
            np.asarray(table[self.tenant_col])
            if self.tenant_col and self.tenant_col in table
            else np.zeros(len(table), np.int64)
        )
        users = np.asarray(table[self.user_col], np.int64)
        ress = np.asarray(table[self.res_col], np.int64)
        counts = (
            np.asarray(table[self.likelihood_col], np.float64)
            if self.likelihood_col and self.likelihood_col in table
            else np.ones(len(table))
        )
        factors: Dict = {}
        stats: Dict = {}
        rank = int(self.rank)
        l2 = jnp.float32(self.reg_param)
        for t in np.unique(tenants):
            m = tenants == t
            u, r, c = users[m], ress[m], counts[m]
            n_users, n_res = int(u.max()) + 1, int(r.max()) + 1
            # dedupe observed pairs, summing counts (sparse COO)
            pair_key = u.astype(np.int64) * n_res + r
            uniq, inv = np.unique(pair_key, return_inverse=True)
            acc = np.zeros(len(uniq), np.float64)
            np.add.at(acc, inv, c)
            uu = (uniq // n_res).astype(np.int32)
            rr = (uniq % n_res).astype(np.int32)
            affinity = np.log1p(acc).astype(np.float32)
            # unobserved cells participate with weight complement_ratio and
            # target 0 (the reference samples explicit complement zeros);
            # the sparse sweep never materializes the dense matrix
            c0 = jnp.float32(min(max(float(self.complement_ratio), 0.0), 1.0))
            uu_j, rr_j = jnp.asarray(uu), jnp.asarray(rr)
            a_j = jnp.asarray(affinity)
            key = jax.random.PRNGKey(int(self.seed))
            X = jax.random.normal(key, (n_users, rank), jnp.float32) * 0.1
            Y = jax.random.normal(
                jax.random.fold_in(key, 1), (n_res, rank), jnp.float32
            ) * 0.1
            for _ in range(int(self.max_iter)):
                X = _als_step_sparse(Y, uu_j, rr_j, a_j, c0, l2, rank,
                                     n_users)
                Y = _als_step_sparse(X, rr_j, uu_j, a_j, c0, l2, rank, n_res)
            X, Y = np.asarray(X), np.asarray(Y)
            factors[t] = (X, Y)
            # standardization stats over OBSERVED pairs' predicted affinity
            pred = np.einsum("ij,ij->i", X[uu], Y[rr])
            stats[t] = (float(pred.mean()), float(pred.std() + 1e-9))
        return AccessAnomalyModel(
            factors=factors, stats=stats,
            tenant_col=self.tenant_col, user_col=self.user_col,
            res_col=self.res_col, output_col=self.output_col,
        )


@register_stage
class AccessAnomalyModel(Model):
    tenant_col = Param("tenant column", default="")
    user_col = Param("indexed user column", default="user")
    res_col = Param("indexed resource column", default="res")
    output_col = Param("anomaly score column", default="anomaly_score")
    factors = ComplexParam("per-tenant (user_factors, res_factors)")
    stats = ComplexParam("per-tenant (mean, std) of observed affinity")

    def _transform(self, table: Table) -> Table:
        tenants = (
            np.asarray(table[self.tenant_col])
            if self.tenant_col and self.tenant_col in table
            else np.zeros(len(table), np.int64)
        )
        users = np.asarray(table[self.user_col], np.int64)
        ress = np.asarray(table[self.res_col], np.int64)
        out = np.zeros(len(table), np.float64)
        for t in np.unique(tenants):
            m = tenants == t
            if t not in self.factors:
                out[m] = np.nan
                continue
            X, Y = self.factors[t]
            mean, std = self.stats[t]
            u, r = users[m], ress[m]
            ok = (u >= 0) & (u < X.shape[0]) & (r >= 0) & (r < Y.shape[0])
            pred = np.zeros(m.sum())
            pred[ok] = np.einsum("ij,ij->i", X[u[ok]], Y[r[ok]])
            # unseen user/resource: affinity 0 (maximally unexpected)
            out[m] = -(pred - mean) / std
        return table.with_column(self.output_col, out)
