"""Elastic multi-host runtime: rendezvous, membership, host-death detection.

The control-plane replacement for the reference's driver socket handshakes
(lightgbm/LightGBMBase.scala:392-430 createDriverNodesThread: ServerSocket
rendezvous collecting host:port from every task; vw/VowpalWabbitBase.scala:
434-462 spanning-tree daemon).  On TPU the data plane is XLA collectives
over ICI/DCN, not TCP rings — what remains OURS to build is everything the
reference's driver did around the ring:

* **Rendezvous** — `initialize_distributed` joins the jax coordination
  service with bounded retries, full-jitter backoff and a hard deadline,
  every attempt crossing the `dist.rendezvous` fault point.  A "already
  initialized" runtime (standard on Cloud TPU VMs) is detected precisely,
  not by substring accident.
* **Membership** — an epoch-numbered view of the pod (host id, process
  index, addressable device count) persisted by the coordinator through
  :class:`MembershipStore` with the durable tmp+fsync+rename idiom.  A
  view can only advance: publishing a stale epoch raises
  :class:`StaleMembershipError` (and counts ``dist.membership.stale``).
  The store doubles as a file-based rendezvous/heartbeat plane for
  backends whose coordination service cannot host one (the CPU soak's
  "gloo/proxy" stand-in) — the same API a TPU pod drives over the real
  coordination service.
* **Host-death detection** — :class:`HeartbeatMonitor`, a lease monitor
  (clock-injectable, so tests script lease expiry under a
  ``VirtualClock``) that declares a silent peer lost EXACTLY once:
  ``dist.host.lost`` counter + record, instead of the loss being
  discovered by a wedged allreduce.  Beats cross the ``dist.heartbeat``
  fault point; an injected drop is a lost heartbeat message
  (``dist.heartbeat.missed``), not an error.
* **Hang-budget collectives** — `run_with_deadline` bounds every
  collective entry (`barrier`, the elastic trainer's step) by a wall
  budget, turning a silent wedge into a :class:`CollectiveTimeout`.
* **Elasticity** — :class:`ElasticContext`, the per-step harness
  `fit_epochs_resumable` polls: beat own lease, detect/adopt peer loss
  (coordinator detects via the monitor; followers adopt the coordinator's
  shrunken epoch), and rebuild the mesh over the survivors.  The
  ``training.host_lost`` fault point injects a simulated peer death so
  chaos plans exercise the whole quarantine → shrink → resume ladder.
* **Per-host observability** — :class:`HostTelemetryServer`, a minimal
  ``/metrics.json`` + ``/health`` endpoint serving this host's
  ``export_snapshot`` in exactly the wire format the PR 15 federation
  (`core/telemetry/fleet.py` ``merge_snapshots``) merges, so
  ``/fleet/metrics`` shows the pod, not the process.

Registry notes: fault points are rows in docs/robustness.md (graftlint
G301/G302); counters/gauges are declared in
core/telemetry/metrics.py ``DECLARED_METRICS`` (metrics-lint M001).
"""
from __future__ import annotations

import inspect
import json
import os
import random
import re
import socket
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..core import telemetry as core_telemetry
from ..utils.faults import InjectedFault, fault_point, monotonic, sleep
from ..utils.sync import make_lock

__all__ = [
    "initialize_distributed",
    "barrier",
    "is_coordinator",
    "reset_distributed_state",
    "run_with_deadline",
    "RendezvousError",
    "StaleMembershipError",
    "CollectiveTimeout",
    "HostInfo",
    "local_host_info",
    "MembershipView",
    "MembershipStore",
    "HeartbeatMonitor",
    "ElasticContext",
    "HostTelemetryServer",
    "DIST_FAULT_POINTS",
]

# the programmatic registry tools/chaos_soak.py --dist arms (mirrors
# flow_fault_points(): a new point added here is covered automatically,
# and the soak's stale-config check fails if a scripted point vanishes)
DIST_FAULT_POINTS = ("dist.rendezvous", "dist.heartbeat",
                     "training.host_lost")


class RendezvousError(RuntimeError):
    """Joining the multi-host job failed past the retry/deadline budget."""


class StaleMembershipError(ValueError):
    """A membership view with a non-advancing epoch was published or
    required — acting on it would resurrect a dead host's devices."""


class CollectiveTimeout(TimeoutError):
    """A collective entry exceeded its hang budget.  The underlying XLA
    call cannot be cancelled (the worker thread is abandoned as daemon);
    this makes the wedge a loud, typed event instead of slow training."""


# ---------------------------------------------------------------------------
# Rendezvous: hardened jax.distributed.initialize
# ---------------------------------------------------------------------------

# precise already-initialized detection: the runtime's message is
# "Distributed system is already initialized" — matching any "already"
# substring (the old behavior) swallowed e.g. deadline errors too
_ALREADY_INITIALIZED = re.compile(r"already\s+initial", re.IGNORECASE)

_INITIALIZED = {"done": False}


def reset_distributed_state() -> None:
    """Test seam: forget the module-level initialized latch (the real
    jax runtime state, if any, is NOT torn down)."""
    _INITIALIZED["done"] = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    max_attempts: int = 3,
    backoff_s: float = 0.5,
    timeout_s: float = 120.0,
    seed: int = 0,
    _initialize: Optional[Callable] = None,
) -> None:
    """Join the multi-host job.  No-ops for single-process jobs and when
    the TPU runtime already auto-initialized (standard on Cloud TPU VMs).
    Env fallbacks: COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID.

    Each attempt crosses the ``dist.rendezvous`` fault point; transient
    failures retry up to `max_attempts` times with full-jitter backoff
    (``uniform(0, backoff_s * 2**attempt)``) through the injectable
    `utils.faults` clock, all under one hard `timeout_s` deadline.  The
    remaining deadline is also passed to the runtime as its per-attempt
    ``initialization_timeout`` when supported.  Counters:
    ``dist.rendezvous.attempt`` / ``.retry`` / ``.failed`` and the
    ``dist.rendezvous.latency`` histogram on success.

    `_initialize` is the test seam replacing ``jax.distributed.initialize``.
    """
    if _INITIALIZED["done"]:
        return
    coordinator_address = (coordinator_address
                           or os.environ.get("COORDINATOR_ADDRESS"))
    if coordinator_address is None:
        # single host — note: do NOT touch jax.process_count() before this
        # point; it would initialize the local backend and make a later
        # jax.distributed.initialize impossible
        _INITIALIZED["done"] = True
        return
    init = _initialize if _initialize is not None \
        else jax.distributed.initialize
    kwargs = dict(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes
                          or os.environ.get("NUM_PROCESSES", 1)),
        process_id=int(process_id if process_id is not None
                       else os.environ.get("PROCESS_ID", 0)),
    )
    takes_timeout = False
    try:
        takes_timeout = ("initialization_timeout"
                         in inspect.signature(init).parameters)
    except (TypeError, ValueError):
        pass
    rng = random.Random(f"{seed}:dist.rendezvous")
    deadline = monotonic() + float(timeout_s)
    last: Optional[BaseException] = None
    for attempt in range(max(1, int(max_attempts))):
        core_telemetry.incr("dist.rendezvous.attempt")
        t0 = monotonic()
        try:
            fault_point("dist.rendezvous")
            if takes_timeout:
                remaining = max(1.0, deadline - monotonic())
                init(initialization_timeout=int(remaining), **kwargs)
            else:
                init(**kwargs)
            core_telemetry.histogram("dist.rendezvous.latency").observe(
                monotonic() - t0)
            _INITIALIZED["done"] = True
            return
        except RuntimeError as e:
            if _ALREADY_INITIALIZED.search(str(e)):
                # the runtime auto-initialized: joined, not failed
                _INITIALIZED["done"] = True
                return
            last = e
        except (InjectedFault, OSError) as e:
            last = e
        if attempt + 1 >= max(1, int(max_attempts)):
            break
        delay = rng.uniform(0.0, float(backoff_s) * (2.0 ** attempt))
        if monotonic() + delay >= deadline:
            break
        core_telemetry.incr("dist.rendezvous.retry")
        sleep(delay)
    core_telemetry.incr("dist.rendezvous.failed")
    raise RendezvousError(
        f"rendezvous with {coordinator_address} failed after "
        f"{max_attempts} attempts / {timeout_s:.0f}s deadline: "
        f"{last!r}") from last


# ---------------------------------------------------------------------------
# Hang-budget collectives
# ---------------------------------------------------------------------------

def run_with_deadline(fn: Callable, budget_s: Optional[float],
                      name: str = "collective"):
    """Run `fn()` under a wall-clock hang budget.  `budget_s=None` runs
    inline.  On overrun, counts ``dist.collective.overrun`` and raises
    :class:`CollectiveTimeout`; the worker thread is abandoned (daemon) —
    a wedged XLA collective cannot be cancelled, only *detected*, which
    is exactly the property a host death must have (docs/robustness.md
    "Elastic multi-host")."""
    if budget_s is None:
        return fn()
    box: Dict[str, object] = {}
    done = threading.Event()

    def _run():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name=f"dist-deadline-{name}")
    t.start()
    if not done.wait(timeout=float(budget_s)):
        core_telemetry.incr("dist.collective.overrun")
        # the spent budget is wall-clock the step can never get back:
        # attribute it to the goodput ledger's `collective` bucket
        # (no-op unless training has started)
        core_telemetry.LEDGER.note_lost("collective", float(budget_s))
        raise CollectiveTimeout(
            f"{name} exceeded its {float(budget_s):g}s hang budget")
    if "error" in box:
        raise box["error"]  # type: ignore[misc]
    return box.get("result")


def barrier(name: str = "barrier",
            timeout_s: Optional[float] = 60.0) -> None:
    """Gang-sync all hosts (BarrierTaskContext.barrier() analog,
    lightgbm/TrainUtils.scala:259-266).  A tiny psum across all devices
    forces a global collective, which only completes when every host
    participates — now bounded by `timeout_s` (counts
    ``dist.barrier.timeout`` and raises :class:`CollectiveTimeout`
    instead of blocking forever on a dead peer)."""

    def _sync():
        x = jax.numpy.ones((jax.local_device_count(),))
        out = jax.pmap(lambda v: jax.lax.psum(v, axis_name="i"),
                       axis_name="i")(x)
        np.asarray(out)  # block

    try:
        run_with_deadline(_sync, timeout_s, name=f"barrier.{name}")
    except CollectiveTimeout:
        core_telemetry.incr("dist.barrier.timeout")
        raise


def is_coordinator() -> bool:
    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# Membership: epoch-numbered views of the pod
# ---------------------------------------------------------------------------

class HostInfo:
    """One host's identity in a membership view: id, process index, and
    its addressable device count (the data-axis capacity it brings)."""

    __slots__ = ("host_id", "process_index", "num_devices", "address")

    def __init__(self, host_id: str, process_index: int,
                 num_devices: int, address: str = ""):
        self.host_id = str(host_id)
        self.process_index = int(process_index)
        self.num_devices = int(num_devices)
        self.address = str(address)

    def to_dict(self) -> dict:
        return {"host_id": self.host_id,
                "process_index": self.process_index,
                "num_devices": self.num_devices,
                "address": self.address}

    @classmethod
    def from_dict(cls, doc: dict) -> "HostInfo":
        return cls(doc["host_id"], doc["process_index"],
                   doc["num_devices"], doc.get("address", ""))

    def __eq__(self, other) -> bool:
        return (isinstance(other, HostInfo)
                and self.to_dict() == other.to_dict())

    def __repr__(self) -> str:
        return (f"HostInfo({self.host_id!r}, rank={self.process_index}, "
                f"devices={self.num_devices})")


def local_host_info(host_id: Optional[str] = None) -> HostInfo:
    """This process's HostInfo (id defaults to ``host-<process_index>``).
    Touches the backend — call only after `initialize_distributed`."""
    rank = jax.process_index()
    return HostInfo(
        host_id if host_id is not None else f"host-{rank}",
        rank, jax.local_device_count(),
        address=socket.gethostname())


class MembershipView:
    """One epoch of pod membership.  Epochs only advance: every shrink
    (or join) is a NEW view, so survivors can reject decisions made
    against a stale roster (:meth:`require_epoch`)."""

    def __init__(self, epoch: int, hosts: Sequence[HostInfo]):
        if int(epoch) < 1:
            raise ValueError(f"membership epochs start at 1, got {epoch}")
        self.epoch = int(epoch)
        self.hosts: List[HostInfo] = sorted(
            hosts, key=lambda h: (h.process_index, h.host_id))
        if len({h.host_id for h in self.hosts}) != len(self.hosts):
            raise ValueError("duplicate host ids in membership view")

    @property
    def host_ids(self) -> List[str]:
        return [h.host_id for h in self.hosts]

    @property
    def total_devices(self) -> int:
        return sum(h.num_devices for h in self.hosts)

    def data_axis(self, model: int = 1, pipe: int = 1) -> int:
        """The data-parallel degree a `MeshPlan(data=-1, model, pipe)`
        over this view's devices would absorb."""
        n = self.total_devices
        if n % (model * pipe) != 0:
            raise ValueError(
                f"{n} devices not divisible by model*pipe={model * pipe}")
        return n // (model * pipe)

    def without(self, *lost_ids: str) -> "MembershipView":
        """The next epoch minus `lost_ids` (the shrink-and-resume view)."""
        gone = set(lost_ids)
        missing = gone - set(self.host_ids)
        if missing:
            raise KeyError(f"hosts not in epoch {self.epoch}: "
                           f"{sorted(missing)}")
        survivors = [h for h in self.hosts if h.host_id not in gone]
        if not survivors:
            raise ValueError("cannot shrink to an empty membership view")
        return MembershipView(self.epoch + 1, survivors)

    def require_epoch(self, expected: int) -> None:
        """Raise :class:`StaleMembershipError` unless this view IS epoch
        `expected` — the guard every epoch-scoped decision runs first."""
        if self.epoch != int(expected):
            core_telemetry.incr("dist.membership.stale")
            raise StaleMembershipError(
                f"membership epoch {self.epoch} != required {expected}")

    def to_dict(self) -> dict:
        return {"epoch": self.epoch,
                "hosts": [h.to_dict() for h in self.hosts]}

    @classmethod
    def from_dict(cls, doc: dict) -> "MembershipView":
        return cls(doc["epoch"],
                   [HostInfo.from_dict(h) for h in doc["hosts"]])

    def __repr__(self) -> str:
        return (f"MembershipView(epoch={self.epoch}, "
                f"hosts={self.host_ids}, devices={self.total_devices})")


def _atomic_write_json(path: str, doc: dict) -> None:
    # tmp + fsync + rename: a crash mid-write leaves the previous file,
    # never a torn one (the G404-enforced durable-write idiom).  The tmp
    # name is per-writer: heartbeats come from both a dedicated beater
    # thread and loop code, and two writers sharing one tmp path race
    # each other's os.replace into FileNotFoundError.
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class MembershipStore:
    """Coordinator-persisted membership + a file-based rendezvous and
    heartbeat plane.

    Layout under `root`::

        hosts/<host_id>.json   registrations (rendezvous intake)
        beats/<host_id>.json   monotone heartbeat sequence numbers
        membership.json        the current MembershipView (atomic)

    On a real pod the same API rides the jax coordination service's
    key-value store; the file plane is the CPU-backend stand-in that
    lets multi-process soaks run anywhere (tools/dist_soak.py).  Beat
    *freshness* is judged by sequence advance observed through the
    monitor's own injectable clock — never by comparing wall clocks
    across processes."""

    def __init__(self, root):
        self.root = os.fspath(root)
        self._hosts_dir = os.path.join(self.root, "hosts")
        self._beats_dir = os.path.join(self.root, "beats")
        os.makedirs(self._hosts_dir, exist_ok=True)
        os.makedirs(self._beats_dir, exist_ok=True)
        self._path = os.path.join(self.root, "membership.json")
        self._lock = make_lock("parallel.dist.membership")
        self._beat_seq: Dict[str, int] = {}  #: guarded-by self._lock

    # ---- registration / view -------------------------------------------

    def register(self, info: HostInfo) -> None:
        _atomic_write_json(
            os.path.join(self._hosts_dir, f"{info.host_id}.json"),
            info.to_dict())

    def registered(self) -> List[HostInfo]:
        out = []
        for fn in sorted(os.listdir(self._hosts_dir)):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._hosts_dir, fn)) as f:
                    out.append(HostInfo.from_dict(json.load(f)))
            except (OSError, ValueError, KeyError):
                continue  # torn mid-write: the next poll sees it whole
        return out

    def publish(self, view: MembershipView) -> MembershipView:
        """Coordinator-only: persist `view`.  The epoch must strictly
        advance past the stored one (stale publishes raise — a delayed
        coordinator must not resurrect a dead host's devices)."""
        current = self.load()
        if current is not None and view.epoch <= current.epoch:
            core_telemetry.incr("dist.membership.stale")
            raise StaleMembershipError(
                f"cannot publish epoch {view.epoch} over "
                f"epoch {current.epoch}")
        _atomic_write_json(self._path, view.to_dict())
        core_telemetry.incr("dist.membership.update")
        core_telemetry.gauge("dist.membership.epoch").set(view.epoch)
        core_telemetry.gauge("dist.membership.hosts").set(len(view.hosts))
        return view

    def load(self) -> Optional[MembershipView]:
        try:
            with open(self._path) as f:
                return MembershipView.from_dict(json.load(f))
        except (OSError, ValueError, KeyError):
            return None

    # ---- rendezvous -----------------------------------------------------

    def rendezvous(self, info: HostInfo, expected: int,
                   coordinator: bool = False,
                   timeout_s: float = 60.0,
                   poll_s: float = 0.05,
                   seed: int = 0) -> MembershipView:
        """File-plane rendezvous: register, then either collect `expected`
        registrations and publish epoch 1 (coordinator) or wait for the
        published view (followers).  Registration attempts cross the
        ``dist.rendezvous`` fault point with full-jitter retries under a
        hard deadline, same contract as `initialize_distributed`."""
        rng = random.Random(f"{seed}:{info.host_id}:dist.rendezvous")
        deadline = monotonic() + float(timeout_s)
        attempt = 0
        while True:
            core_telemetry.incr("dist.rendezvous.attempt")
            try:
                fault_point("dist.rendezvous")
                self.register(info)
                break
            except (InjectedFault, OSError) as e:
                if monotonic() >= deadline:
                    core_telemetry.incr("dist.rendezvous.failed")
                    raise RendezvousError(
                        f"{info.host_id} could not register within "
                        f"{timeout_s:.0f}s: {e!r}") from e
                core_telemetry.incr("dist.rendezvous.retry")
                sleep(min(rng.uniform(0.0, 0.05 * (2.0 ** attempt)),
                          max(0.0, deadline - monotonic())))
                attempt += 1
        t0 = monotonic()
        while monotonic() < deadline:
            if coordinator:
                roster = self.registered()
                if len(roster) >= int(expected):
                    view = self.load()
                    if view is None:
                        view = self.publish(MembershipView(1, roster))
                    dt = monotonic() - t0
                    core_telemetry.histogram(
                        "dist.rendezvous.latency").observe(dt)
                    # mid-training re-rendezvous is lost wall (the
                    # ledger drops this before training starts)
                    core_telemetry.LEDGER.note_lost("rendezvous", dt)
                    return view
            else:
                view = self.load()
                if view is not None:
                    dt = monotonic() - t0
                    core_telemetry.histogram(
                        "dist.rendezvous.latency").observe(dt)
                    core_telemetry.LEDGER.note_lost("rendezvous", dt)
                    return view
            sleep(poll_s)
        core_telemetry.incr("dist.rendezvous.failed")
        raise RendezvousError(
            f"{info.host_id} rendezvous timed out after {timeout_s:.0f}s "
            f"({len(self.registered())}/{expected} hosts registered)")

    # ---- heartbeats -----------------------------------------------------

    def heartbeat(self, host_id: str) -> None:
        """Bump this host's monotone beat sequence on the shared plane."""
        with self._lock:
            seq = self._beat_seq.get(host_id, 0) + 1
            self._beat_seq[host_id] = seq
        _atomic_write_json(
            os.path.join(self._beats_dir, f"{host_id}.json"),
            {"host_id": host_id, "seq": seq})

    def read_beats(self) -> Dict[str, int]:
        """host_id -> latest beat sequence (the HeartbeatMonitor `source`)."""
        out: Dict[str, int] = {}
        for fn in os.listdir(self._beats_dir):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._beats_dir, fn)) as f:
                    doc = json.load(f)
                out[str(doc["host_id"])] = int(doc["seq"])
            except (OSError, ValueError, KeyError):
                continue
        return out


# ---------------------------------------------------------------------------
# Host-death detection: the heartbeat/lease monitor
# ---------------------------------------------------------------------------

class HeartbeatMonitor:
    """Lease-based liveness: a host whose beats stop for longer than
    `lease_s` is declared lost EXACTLY once (``dist.host.lost`` counter +
    record + `on_lost` callback), so the loss is *detected* — not
    discovered by a wedged allreduce.

    Beats arrive either in-process (:meth:`beat`, crossing the
    ``dist.heartbeat`` fault point — an injected fault models a dropped
    heartbeat message, counted ``dist.heartbeat.missed``) or from a
    shared plane via :meth:`ingest` (sequence-advance semantics: lease
    age is measured on THIS monitor's injectable clock, never by
    comparing wall clocks across hosts).  `start()` runs the poll loop
    on a non-daemon ``dist-heartbeat-monitor`` thread (covered by the
    conftest leak check); tests drive :meth:`check_now` directly under a
    ``VirtualClock``."""

    def __init__(self, hosts: Sequence[str],
                 lease_s: float = 5.0,
                 poll_s: float = 0.25,
                 clock: Optional[Callable[[], float]] = None,
                 on_lost: Optional[Callable[[str, dict], None]] = None,
                 source: Optional[Callable[[], Dict[str, int]]] = None,
                 self_id: Optional[str] = None):
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self._clock = clock if clock is not None else monotonic
        self.on_lost = on_lost
        self._source = source
        self.self_id = self_id
        self._lock = make_lock("parallel.dist.heartbeat")
        now = self._clock()
        # every tracked host starts with a full lease at construction
        self._last: Dict[str, float] = {str(h): now for h in hosts}  #: guarded-by self._lock
        self._seqs: Dict[str, int] = {}  #: guarded-by self._lock
        self.lost: Dict[str, dict] = {}  #: guarded-by self._lock
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- beats ----------------------------------------------------------

    def beat(self, host_id: str) -> bool:
        """Record one in-process heartbeat.  Returns False when the beat
        was dropped (injected ``dist.heartbeat`` fault)."""
        try:
            fault_point("dist.heartbeat")
        except InjectedFault:
            core_telemetry.incr("dist.heartbeat.missed")
            return False
        with self._lock:
            self._last[str(host_id)] = self._clock()
        return True

    def ingest(self, seqs: Dict[str, int]) -> None:
        """Fold shared-plane beat sequences in: a host whose sequence
        ADVANCED since the last ingest beat "now" on this clock."""
        now = self._clock()
        with self._lock:
            for host, seq in seqs.items():
                host = str(host)
                if host not in self._last:
                    continue  # not in the tracked roster
                if self._seqs.get(host) != int(seq):
                    self._seqs[host] = int(seq)
                    self._last[host] = now

    # ---- detection ------------------------------------------------------

    def check_now(self) -> List[str]:
        """Evaluate every lease; returns the hosts NEWLY declared lost
        (each host fires at most once, ever — the `lost` latch)."""
        now = self._clock()
        newly: List[str] = []
        with self._lock:
            for host, last in self._last.items():
                if host in self.lost or host == self.self_id:
                    continue
                age = now - last
                if age > self.lease_s:
                    self.lost[host] = {
                        "host_id": host, "kind": "lease_expired",
                        "last_beat_age_s": round(age, 3),
                        "lease_s": self.lease_s,
                    }
                    newly.append(host)
        for host in newly:
            # outside the lock: counters/callback must not serialize beats
            self._announce(host)
        return newly

    def declare_lost(self, host_id: str,
                     record: Optional[dict] = None) -> bool:
        """Declare `host_id` lost out-of-band (an injected
        ``training.host_lost`` fault, an operator decision).  Same
        exactly-once latch and announcement as a lease expiry."""
        host = str(host_id)
        with self._lock:
            if host in self.lost:
                return False
            rec = {"host_id": host, "kind": "declared"}
            rec.update(record or {})
            self.lost[host] = rec
        self._announce(host)
        return True

    def _announce(self, host: str) -> None:
        core_telemetry.incr("dist.host.lost")
        core_telemetry.incr(f"dist.host.lost.{host}")
        with self._lock:
            rec = dict(self.lost[host])
        with core_telemetry.span("dist.host.lost") as sp:
            sp.attrs.update(rec)
        if self.on_lost is not None:
            self.on_lost(host, rec)

    def alive(self) -> List[str]:
        with self._lock:
            return [h for h in self._last if h not in self.lost]

    # ---- lifecycle ------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "HeartbeatMonitor":
        if not self.running:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._run, name="dist-heartbeat-monitor",
                daemon=False)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_evt.wait(timeout=self.poll_s):
            if self._source is not None:
                try:
                    self.ingest(self._source())
                except Exception:  # noqa: BLE001 — a torn read is a missed poll
                    pass
            self.check_now()

    def stop(self) -> None:
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self) -> "HeartbeatMonitor":
        return self.start()

    def __exit__(self, *_exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# Elasticity: the per-step harness the training loop polls
# ---------------------------------------------------------------------------

class ElasticContext:
    """Glue between the membership plane and `fit_epochs_resumable`'s
    elastic mode.  Once per step the loop calls :meth:`poll`:

    1. beat this host's lease (store and/or in-process monitor);
    2. cross the ``training.host_lost`` fault point — an injected fault
       simulates the death of the next live peer, driving the exact
       same downstream ladder as a real lease expiry;
    3. coordinator: ingest shared-plane beats + evaluate leases;
       follower: adopt a newer epoch the coordinator published.

    A non-empty return is the list of peers lost since the last poll;
    the loop then runs the quarantine → checkpoint-floor rollback →
    :meth:`commit_loss` (epoch advance) → :meth:`rebuild` (mesh over the
    survivors) ladder (docs/robustness.md "Elastic multi-host")."""

    def __init__(self, host: HostInfo, view: MembershipView,
                 store: Optional[MembershipStore] = None,
                 monitor: Optional[HeartbeatMonitor] = None,
                 coordinator: Optional[bool] = None,
                 rebuild: Optional[Callable[[MembershipView],
                                            Optional[tuple]]] = None,
                 hang_budget_s: Optional[float] = None):
        self.host = host
        self.view = view
        self.store = store
        self.monitor = monitor
        self.coordinator = (coordinator if coordinator is not None
                            else host.process_index == 0)
        self._rebuild = rebuild
        self.hang_budget_s = hang_budget_s
        self._lock = make_lock("parallel.dist.elastic")
        self._pending: List[str] = []  #: guarded-by self._lock
        if monitor is not None and monitor.on_lost is None:
            monitor.on_lost = self._notice
        core_telemetry.gauge("dist.membership.epoch").set(view.epoch)
        core_telemetry.gauge("dist.membership.hosts").set(len(view.hosts))

    def _notice(self, host_id: str, record: dict) -> None:
        with self._lock:
            self._pending.append(str(host_id))

    def _next_live_peer(self) -> Optional[str]:
        lost = set(self.monitor.lost) if self.monitor is not None else set()
        with self._lock:
            lost |= set(self._pending)
        for h in self.view.host_ids:
            if h != self.host.host_id and h not in lost:
                return h
        return None

    def poll(self) -> Optional[List[str]]:
        """One elastic tick; returns newly lost peers (None when quiet)."""
        roster = set(self.view.host_ids)  # pre-adoption: the epoch we ran
        if self.store is not None:
            self.store.heartbeat(self.host.host_id)
        if self.monitor is not None:
            self.monitor.beat(self.host.host_id)
        try:
            fault_point("training.host_lost")
        except InjectedFault:
            victim = self._next_live_peer()
            if victim is not None:
                if self.monitor is not None:
                    self.monitor.declare_lost(
                        victim, {"kind": "injected"})
                else:
                    self._notice(victim, {"kind": "injected"})
        if self.monitor is not None and self.coordinator:
            if self.store is not None:
                self.monitor.ingest(self.store.read_beats())
            self.monitor.check_now()
        elif self.store is not None and not self.coordinator:
            latest = self.store.load()
            if latest is not None and latest.epoch > self.view.epoch:
                gone = set(self.view.host_ids) - set(latest.host_ids)
                with self._lock:
                    self._pending.extend(sorted(gone))
                self.view = latest
                core_telemetry.gauge("dist.membership.epoch").set(
                    latest.epoch)
                core_telemetry.gauge("dist.membership.hosts").set(
                    len(latest.hosts))
        with self._lock:
            pending, self._pending = self._pending, []
        # de-dup while keeping order; drop hosts that were already gone
        # from the roster BEFORE this poll (a repeated announcement)
        seen: List[str] = []
        for h in pending:
            if h not in seen and h in roster:
                seen.append(h)
        return seen or None

    def commit_loss(self, lost: Sequence[str]) -> MembershipView:
        """Advance the membership epoch past `lost`.  The coordinator
        publishes the shrunken view (stale publishes raise); followers
        that already adopted the published epoch keep it."""
        gone = [h for h in lost if h in self.view.host_ids]
        if not gone:
            return self.view
        new_view = self.view.without(*gone)
        if self.store is not None and self.coordinator:
            self.store.publish(new_view)
        else:
            core_telemetry.gauge("dist.membership.epoch").set(
                new_view.epoch)
            core_telemetry.gauge("dist.membership.hosts").set(
                len(new_view.hosts))
        self.view = new_view
        return new_view

    def rebuild(self, view: MembershipView) -> Optional[tuple]:
        """The survivor-mesh hook: `(mesh, step_fn)` from the caller's
        rebuild callback (re-running `MeshPlan` with the shrunken data
        axis), or None when the local layout is unchanged."""
        if self._rebuild is None:
            return None
        return self._rebuild(view)


# ---------------------------------------------------------------------------
# Per-host telemetry endpoint (the federation wire format)
# ---------------------------------------------------------------------------

class HostTelemetryServer:
    """Minimal per-host observability endpoint: ``/metrics.json`` serves
    this process's ``export_snapshot`` — byte-compatible with what
    `serving.fleet.FleetTelemetry.pull_once` scrapes from replicas — and
    ``/health`` serves liveness, so a pod's hosts federate into one
    ``/fleet/metrics`` view through the PR 15 ``merge_snapshots`` plane
    without running a full WorkerServer."""

    def __init__(self, host_id: str, host: str = "127.0.0.1",
                 port: int = 0):
        self.host_id = str(host_id)
        self.host = host
        self.port = int(port)
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> Tuple[str, int]:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        host_id = self.host_id

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/")
                if path == "/metrics.json":
                    payload = json.dumps(
                        core_telemetry.export_snapshot(
                            include_spans=False),
                        default=repr).encode("utf-8")
                elif path == "/health":
                    payload = json.dumps(
                        {"status": "ok",
                         "host_id": host_id}).encode("utf-8")
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):  # quiet
                pass

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = _Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=False,
            name=f"dist-host-telemetry-{self.host_id}")
        self._thread.start()
        return self.host, self.port

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self) -> "HostTelemetryServer":
        self.start()
        return self

    def __exit__(self, *_exc) -> None:
        self.stop()
