"""Multi-host rendezvous + barrier: the control-plane replacement for the
reference's driver socket handshakes.

Reference: lightgbm/LightGBMBase.scala:392-430 (createDriverNodesThread:
ServerSocket rendezvous collecting host:port from every task) and
vw/VowpalWabbitBase.scala:434-462 (spanning-tree daemon) — on TPU both are
replaced by `jax.distributed.initialize` against the coordination service;
data-plane AllReduce is XLA collectives over ICI/DCN, not TCP rings.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

__all__ = ["initialize_distributed", "barrier", "is_coordinator"]

_INITIALIZED = {"done": False}


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host job.  No-ops for single-process jobs and when the
    TPU runtime already auto-initialized (standard on Cloud TPU VMs).
    Env fallbacks: COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID.
    """
    if _INITIALIZED["done"]:
        return
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator_address is None:
        # single host — note: do NOT touch jax.process_count() before this
        # point; it would initialize the local backend and make a later
        # jax.distributed.initialize impossible
        _INITIALIZED["done"] = True
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=int(num_processes or os.environ.get("NUM_PROCESSES", 1)),
            process_id=int(process_id if process_id is not None else os.environ.get("PROCESS_ID", 0)),
        )
    except RuntimeError as e:
        if "already" not in str(e).lower():  # runtime auto-initialized is fine
            raise
    _INITIALIZED["done"] = True


def barrier(name: str = "barrier") -> None:
    """Gang-sync all hosts (BarrierTaskContext.barrier() analog,
    lightgbm/TrainUtils.scala:259-266).  A tiny psum across all devices forces
    a global collective, which only completes when every host participates."""
    x = jax.numpy.ones((jax.local_device_count(),))
    out = jax.pmap(lambda v: jax.lax.psum(v, axis_name="i"), axis_name="i")(x)
    np.asarray(out)  # block


def is_coordinator() -> bool:
    return jax.process_index() == 0
