"""Sequence parallelism: ring attention + Ulysses all-to-all attention.

The reference has NO sequence parallelism (SURVEY §2.10: "Not present in
reference" — its longest-sequence handling is CNTK dynamic axes); this module
is the TPU-first upgrade that makes long-context first-class, following the
blockwise-ring construction (Liu et al., Ring Attention) and the
DeepSpeed-Ulysses head-scatter construction, both expressed as XLA
collectives over the mesh:

- ring_attention: K/V blocks rotate around the ICI ring via `ppermute` while
  each device accumulates its queries' attention with a numerically-stable
  online softmax — memory O(S/n) per device, compute fully overlapped.
- ulysses_attention: `all_to_all` reshards (seq-sharded -> head-sharded),
  runs dense per-head attention, and reshards back — cheaper at moderate S,
  requires heads % n == 0.

Both are exact: they match full attention to float tolerance.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map

__all__ = ["full_attention", "ring_attention", "ulysses_attention"]


def full_attention(q, k, v, causal: bool = False):
    """Reference dense attention.  q,k,v: (B, S, H, D) -> (B, S, H, D) f32.

    MXU-friendly mixed precision: the two matmuls run at the INPUT dtype
    (bf16 inputs hit the systolic array at full rate) with f32
    accumulation (`preferred_element_type`); softmax statistics stay f32.
    f32 inputs are bit-identical to the previous formulation.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32)


def _block_accumulate(q, k_blk, v_blk, o, m, l, q_off, k_off, causal: bool):
    """Online-softmax accumulation of one K/V block into (o, m, l).

    q: (B, Sq, H, D) local queries at global offset q_off;
    k_blk/v_blk: (B, Sk, H, D) at global offset k_off.
    o: (B, Sq, H, D) unnormalized; m,l: (B, H, Sq) running max / normalizer.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])
        kpos = k_off + jnp.arange(k_blk.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)                      # (B, H, Sq)
    m_new = jnp.maximum(m, m_blk)
    # fully-masked blocks produce -inf maxima; keep exp() finite
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - m_safe[..., None], -jnp.inf))
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    return o_new, m_new, l_new


def _resolve_axis(mesh: Mesh, axis: Optional[str]) -> str:
    """Default to the mesh's dedicated 'seq' axis when it is populated
    (mesh.py reserves it for sequence parallelism); else fall back to
    'data' so an all-data mesh still works."""
    if axis is not None:
        return axis
    if mesh.shape.get("seq", 1) > 1:
        return "seq"
    return "data"


def _ring_driver(q, k, v, mesh: Mesh, axis: str, accumulate):
    """THE ring protocol, shared by the dense and flash paths: K/V blocks
    rotate via ppermute for n-1 scan steps plus one unscanned final
    block (no wasted last rotation); `accumulate(q_loc, k_blk, v_blk,
    o, m, l, q_off, k_off)` folds one held block into the online-softmax
    carry.  One copy of the offset/rotation math means a fix here fixes
    both paths."""
    n = mesh.shape[axis]
    seq_spec = P(None, axis, None, None)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    )
    def ring(q_loc, k_loc, v_loc):
        idx = jax.lax.axis_index(axis)
        s_loc = q_loc.shape[1]
        q_off = idx * s_loc
        o = jnp.zeros(q_loc.shape, jnp.float32)
        m = jnp.full(
            (q_loc.shape[0], q_loc.shape[2], s_loc), -jnp.inf, jnp.float32
        )
        l = jnp.zeros((q_loc.shape[0], q_loc.shape[2], s_loc), jnp.float32)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, r):
            o, m, l, k_blk, v_blk = carry
            # k/v block currently held came from device (idx - r) mod n
            k_off = ((idx - r) % n) * s_loc
            o, m, l = accumulate(q_loc, k_blk, v_blk, o, m, l, q_off, k_off)
            # rotate: send our block to the next device in the ring
            k_nxt = jax.lax.ppermute(k_blk, axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis, perm)
            return (o, m, l, k_nxt, v_nxt), None

        (o, m, l, k_last, v_last), _ = jax.lax.scan(
            step, (o, m, l, k_loc, v_loc), jnp.arange(n - 1)
        )
        o, m, l = accumulate(q_loc, k_last, v_last, o, m, l, q_off,
                             ((idx - (n - 1)) % n) * s_loc)
        return o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]

    return ring(q, k, v)


def _ring_dense(q, k, v, mesh: Mesh, axis: str, causal: bool):
    """The dense-block ring: per-step [Sq, Sk] score blocks in XLA."""

    def accumulate(q_loc, k_blk, v_blk, o, m, l, q_off, k_off):
        return _block_accumulate(q_loc, k_blk, v_blk, o, m, l,
                                 q_off, k_off, causal)

    return _ring_driver(q, k, v, mesh, axis, accumulate)


def _merge_normalized(o, m, l, o_b, lse_b):
    """Fold one NORMALIZED attention block (o_b, lse_b) into the running
    (o, m, l) online-softmax carry.  A normalized block is a weighted
    value with scalar log-weight lse_b per row — the same (reference,
    weight, weighted-values) algebra _block_accumulate maintains, so
    dense and flash steps can mix freely."""
    m_new = jnp.maximum(m, lse_b)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    w = jnp.where(jnp.isfinite(lse_b), jnp.exp(lse_b - m_safe), 0.0)
    o_new = (o * corr.transpose(0, 2, 1)[..., None]
             + o_b * w.transpose(0, 2, 1)[..., None])
    return o_new, m_new, l * corr + w


def _ring_flash_fwd(q, k, v, mesh: Mesh, axis: str, causal: bool):
    """Ring forward with each block's attention in the Pallas flash
    kernel (VMEM-resident scores; the kernel's lse output is exactly the
    per-block merge statistic) — Liu et al.'s construction with the
    intra-block part on the MXU instead of dense XLA.  Causality between
    BLOCKS is static per relation (behind/diagonal/ahead) but the
    relation itself depends on the device index, so the three cases ride
    lax.cond."""
    from ..ops.attention_kernels import _run_kernel

    def accumulate(q_loc, k_blk, v_blk, o, m, l, q_off, k_off):
        b, s_loc, h, _ = q_loc.shape

        def run(blk_causal):
            o_b, lse = _run_kernel(q_loc, k_blk, v_blk, blk_causal)
            return o_b, lse.reshape(b, h, s_loc)

        def skipped():
            return (jnp.zeros(q_loc.shape, jnp.float32),
                    jnp.full((b, h, s_loc), -jnp.inf, jnp.float32))

        if not causal:
            o_b, lse = run(False)
        else:
            # k block strictly behind the queries -> fully visible;
            # same offset -> the kernel's own causal mask IS the global
            # mask (blocks are equal-sized and aligned); ahead -> skip
            o_b, lse = jax.lax.cond(
                k_off < q_off, lambda: run(False),
                lambda: jax.lax.cond(k_off == q_off,
                                     lambda: run(True), skipped))
        return _merge_normalized(o, m, l, o_b, lse)

    return _ring_driver(q, k, v, mesh, axis, accumulate)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash(q, k, v, mesh, axis, causal):
    """Flash-forward ring with the dense-ring recompute as backward —
    forward traffic drops to the flash shape while gradients stay the
    exact dense-block autodiff (same containment stance as the fused
    kernel took before its flash backward landed)."""
    return _ring_flash_fwd(q, k, v, mesh, axis, causal)


def _ring_flash_f(q, k, v, mesh, axis, causal):
    return _ring_flash_fwd(q, k, v, mesh, axis, causal), (q, k, v)


def _ring_flash_b(mesh, axis, causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _ring_dense(q, k, v, mesh, axis, causal), q, k, v)
    return vjp(g)


_ring_flash.defvjp(_ring_flash_f, _ring_flash_b)


def ring_attention(q, k, v, mesh: Mesh, axis: Optional[str] = None,
                   causal: bool = False):
    """Exact attention with sequence sharded over `axis` (default: the
    mesh's 'seq' axis if populated, else 'data').

    q,k,v: (B, S, H, D) GLOBAL arrays (or already sharded); S must divide by
    the axis size.  Returns (B, S, H, D) with the same sharding.

    When the LOCAL block shape can take the Pallas kernel, each ring
    step's intra-block attention runs VMEM-resident (flash) and blocks
    merge by their logsumexp; otherwise the dense-block path runs.  Both
    are exact vs full attention (tests assert it).
    """
    from ..ops.attention_kernels import kernel_ok

    axis = _resolve_axis(mesh, axis)
    n = mesh.shape[axis]
    blk = q.shape[1] // n
    local = jax.ShapeDtypeStruct((q.shape[0], blk, q.shape[2], q.shape[3]),
                                 q.dtype)
    if kernel_ok(local):
        return _ring_flash(q, k, v, mesh, axis, causal)
    return _ring_dense(q, k, v, mesh, axis, causal)


def ulysses_attention(q, k, v, mesh: Mesh, axis: Optional[str] = None,
                      causal: bool = False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses construction).

    Heads must divide by the axis size: reshard (S/n, H) -> (S, H/n), run
    dense attention on full sequences per head shard, reshard back.
    """
    axis = _resolve_axis(mesh, axis)
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by axis size {n}")
    seq_spec = P(None, axis, None, None)

    @partial(
        shard_map, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    )
    def ulysses(q_loc, k_loc, v_loc):
        def scatter_heads(x):
            # (B, S/n, H, D) -> (B, S, H/n, D)
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        def gather_seq(x):
            # (B, S, H/n, D) -> (B, S/n, H, D)
            return jax.lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        qg, kg, vg = scatter_heads(q_loc), scatter_heads(k_loc), scatter_heads(v_loc)
        # the per-device inner attention is DENSE over the full sequence —
        # exactly the shape the Pallas flash kernel accelerates; it falls
        # back to the XLA composition for shapes it can't take, so this
        # composes sequence parallelism with the VMEM-resident kernel
        from ..ops.attention_kernels import fused_attention

        og = fused_attention(qg, kg, vg, causal)
        return gather_seq(og)

    return ulysses(q, k, v)
