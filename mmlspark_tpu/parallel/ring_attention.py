"""Sequence parallelism: ring attention + Ulysses all-to-all attention.

The reference has NO sequence parallelism (SURVEY §2.10: "Not present in
reference" — its longest-sequence handling is CNTK dynamic axes); this module
is the TPU-first upgrade that makes long-context first-class, following the
blockwise-ring construction (Liu et al., Ring Attention) and the
DeepSpeed-Ulysses head-scatter construction, both expressed as XLA
collectives over the mesh:

- ring_attention: K/V blocks rotate around the ICI ring via `ppermute` while
  each device accumulates its queries' attention with a numerically-stable
  online softmax — memory O(S/n) per device, compute fully overlapped.
- ulysses_attention: `all_to_all` reshards (seq-sharded -> head-sharded),
  runs dense per-head attention, and reshards back — cheaper at moderate S,
  requires heads % n == 0.

Both are exact: they match full attention to float tolerance.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["full_attention", "ring_attention", "ulysses_attention"]


def full_attention(q, k, v, causal: bool = False):
    """Reference dense attention.  q,k,v: (B, S, H, D) -> (B, S, H, D) f32.

    MXU-friendly mixed precision: the two matmuls run at the INPUT dtype
    (bf16 inputs hit the systolic array at full rate) with f32
    accumulation (`preferred_element_type`); softmax statistics stay f32.
    f32 inputs are bit-identical to the previous formulation.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32)


def _block_accumulate(q, k_blk, v_blk, o, m, l, q_off, k_off, causal: bool):
    """Online-softmax accumulation of one K/V block into (o, m, l).

    q: (B, Sq, H, D) local queries at global offset q_off;
    k_blk/v_blk: (B, Sk, H, D) at global offset k_off.
    o: (B, Sq, H, D) unnormalized; m,l: (B, H, Sq) running max / normalizer.
    """
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                   preferred_element_type=jnp.float32) / jnp.sqrt(
        jnp.float32(d))
    if causal:
        qpos = q_off + jnp.arange(q.shape[1])
        kpos = k_off + jnp.arange(k_blk.shape[1])
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)                      # (B, H, Sq)
    m_new = jnp.maximum(m, m_blk)
    # fully-masked blocks produce -inf maxima; keep exp() finite
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - m_safe[..., None], -jnp.inf))
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
        preferred_element_type=jnp.float32,
    )
    return o_new, m_new, l_new


def _resolve_axis(mesh: Mesh, axis: Optional[str]) -> str:
    """Default to the mesh's dedicated 'seq' axis when it is populated
    (mesh.py reserves it for sequence parallelism); else fall back to
    'data' so an all-data mesh still works."""
    if axis is not None:
        return axis
    if mesh.shape.get("seq", 1) > 1:
        return "seq"
    return "data"


def ring_attention(q, k, v, mesh: Mesh, axis: Optional[str] = None,
                   causal: bool = False):
    """Exact attention with sequence sharded over `axis` (default: the
    mesh's 'seq' axis if populated, else 'data').

    q,k,v: (B, S, H, D) GLOBAL arrays (or already sharded); S must divide by
    the axis size.  Returns (B, S, H, D) with the same sharding.
    """
    axis = _resolve_axis(mesh, axis)
    n = mesh.shape[axis]
    seq_spec = P(None, axis, None, None)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    )
    def ring(q_loc, k_loc, v_loc):
        idx = jax.lax.axis_index(axis)
        s_loc = q_loc.shape[1]
        q_off = idx * s_loc
        o = jnp.zeros(q_loc.shape, jnp.float32)
        m = jnp.full(
            (q_loc.shape[0], q_loc.shape[2], s_loc), -jnp.inf, jnp.float32
        )
        l = jnp.zeros((q_loc.shape[0], q_loc.shape[2], s_loc), jnp.float32)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def step(carry, r):
            o, m, l, k_blk, v_blk = carry
            # k/v block currently held came from device (idx - r) mod n
            src = (idx - r) % n
            k_off = src * s_loc
            o, m, l = _block_accumulate(
                q_loc, k_blk, v_blk, o, m, l, q_off, k_off, causal
            )
            # rotate: send our block to the next device in the ring
            k_nxt = jax.lax.ppermute(k_blk, axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis, perm)
            return (o, m, l, k_nxt, v_nxt), None

        # n-1 rotations; the last held block is accumulated without a
        # wasted final ppermute of the full K/V shard
        (o, m, l, k_last, v_last), _ = jax.lax.scan(
            step, (o, m, l, k_loc, v_loc), jnp.arange(n - 1)
        )
        o, m, l = _block_accumulate(
            q_loc, k_last, v_last, o, m, l, q_off,
            ((idx - (n - 1)) % n) * s_loc, causal,
        )
        return o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]

    return ring(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: Optional[str] = None,
                      causal: bool = False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses construction).

    Heads must divide by the axis size: reshard (S/n, H) -> (S, H/n), run
    dense attention on full sequences per head shard, reshard back.
    """
    axis = _resolve_axis(mesh, axis)
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"heads {q.shape[2]} not divisible by axis size {n}")
    seq_spec = P(None, axis, None, None)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    )
    def ulysses(q_loc, k_loc, v_loc):
        def scatter_heads(x):
            # (B, S/n, H, D) -> (B, S, H/n, D)
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        def gather_seq(x):
            # (B, S, H/n, D) -> (B, S/n, H, D)
            return jax.lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        qg, kg, vg = scatter_heads(q_loc), scatter_heads(k_loc), scatter_heads(v_loc)
        # the per-device inner attention is DENSE over the full sequence —
        # exactly the shape the Pallas flash kernel accelerates; it falls
        # back to the XLA composition for shapes it can't take, so this
        # composes sequence parallelism with the VMEM-resident kernel
        from ..ops.attention_kernels import fused_attention

        og = fused_attention(qg, kg, vg, causal)
        return gather_seq(og)

    return ulysses(q, k, v)
