"""Canonical parameter-sharding rules for the model families.

One place for the `(path, arr) -> PartitionSpec` functions that
`models.training.shard_params` consumes — the graft-entry dryrun, tests,
and user code previously each hand-rolled the same name matching.

Rules return None/P() to replicate; XLA inserts the collectives implied
by whatever they shard (tensor parallelism for block kernels, expert
parallelism for MoE expert dims).
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

__all__ = ["path_names", "lm_tensor_parallel_rules",
           "moe_expert_parallel_rules", "head_rules"]


def path_names(path):
    """Flax/jax tree path entries -> their string names."""
    return [getattr(p, "key", getattr(p, "name", "")) for p in path]


def lm_tensor_parallel_rules(path, arr, axis: str = "model"):
    """TransformerLM block/head kernels over the tensor axis: qkv/mlp_in/
    head shard output features, proj/mlp_out shard input features (the
    megatron pairing — one all-reduce per block, none inside the MLP)."""
    names = path_names(path)
    # 'qkv' is the fused MHA projection; GQA splits it into 'q' + 'kv'
    if arr.ndim == 2 and any(n in names for n in
                             ("qkv", "q", "kv", "mlp_in", "head")):
        return P(None, axis)
    if arr.ndim == 2 and any(n in names for n in ("proj", "mlp_out")):
        return P(axis, None)
    return P()


def moe_expert_parallel_rules(path, arr, axis: str = "model"):
    """Shard the EXPERT dim of switch-MoE w_in/w_out (expert parallelism);
    everything else replicates."""
    names = path_names(path)
    if ("moe" in names and arr.ndim == 3
            and any(n in names for n in ("w_in", "w_out"))):
        return P(axis, None, None)
    return P()


def head_rules(path, arr, axis: str = "model"):
    """Classifier-head-only sharding (the CNN fine-tune shape: one big
    dense head, convs replicated)."""
    names = path_names(path)
    if "head" in names and arr.ndim >= 2:
        return P(None, axis)
    return P()
