"""Partition-rule library: regex-over-pytree sharding in the fmengine /
EasyLM style (SNIPPETS.md [1]).

A *rule table* is an ordered sequence of ``(regex, PartitionSpec)``
pairs.  Every parameter leaf is named by its ``/``-joined tree path
(``block0/qkv/kernel``); the FIRST rule whose regex ``re.search``-matches
that name wins, so specific rules go first and a ``(".*", P())``
catch-all closes every table.  Scalars and size-1 leaves always
replicate — a PartitionSpec on a scalar is meaningless and XLA would
reject most of them anyway.

``match_partition_rules(rules, tree)`` turns a table into a spec tree;
``make_shard_and_gather_fns(specs, mesh)`` turns a spec tree into
per-leaf placement/collection closures; ``models.training.shard_params``
consumes either a table or (legacy) a ``(path, arr) -> spec`` callable.
The historical rule callables (``lm_tensor_parallel_rules`` & co.) are
kept as thin adapters over their tables — ONE matcher implementation,
everywhere.

Axis-name hygiene: every axis literal in a spec must be an axis the
mesh actually declares (``parallel.mesh.MESH_AXIS_NAMES``) — a typo'd
axis silently replicates the leaf.  graftlint G501 (né G305) enforces
this statically; ``validate_rules`` enforces it at runtime for
dynamically built tables.  ``PARAM_PATH_MANIFEST`` below is the
coverage side of the same contract: the representative leaf paths the
models actually produce, against which graftlint G503 (and the runtime
``validate_coverage``) prove every table matches every leaf.
"""
from __future__ import annotations

import re
from typing import Iterable, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["path_names", "path_name", "match_partition_rules",
           "spec_for", "make_shard_and_gather_fns", "validate_rules",
           "validate_coverage", "PARAM_PATH_MANIFEST",
           "lm_tensor_rules", "moe_expert_rules", "head_only_rules",
           "lm_3d_rules", "lm_tensor_parallel_rules",
           "moe_expert_parallel_rules", "head_rules"]

RuleTable = Sequence[Tuple[str, P]]

# Representative parameter leaf paths, one per distinct naming shape the
# models emit — the coverage manifest graftlint G503 checks every
# literal rule table against (and `validate_coverage` re-checks at
# runtime for dynamically built tables).  Two layouts are represented:
# the flax ``block{i}`` tree TransformerLM.init produces, and the
# stacked 3D layout ``models.training.lm_params_to_3d`` rearranges it
# into.  Kept a plain tuple literal of string constants: the lint
# AST-parses it (no jax import), same contract as MESH_AXIS_NAMES.
# Adding a differently-named param to a model without a row here is a
# G503 finding; adding a row no table matches is one too.
PARAM_PATH_MANIFEST: Tuple[str, ...] = (
    # flax block{i} layout (TransformerLM / TransformerDecode)
    "tok_embed/embedding",
    "pos_embed/embedding",
    "block0/ln1/scale",
    "block0/ln1/bias",
    "block0/qkv/kernel",
    "block0/q/kernel",
    "block0/kv/kernel",
    "block0/proj/kernel",
    "block0/ln2/scale",
    "block0/mlp_in/kernel",
    "block0/mlp_in/bias",
    "block0/mlp_out/kernel",
    "block0/moe/router/kernel",
    "block0/moe/w_in",
    "block0/moe/w_out",
    "ln_f/scale",
    "head/kernel",
    # stacked 3D layout (models.training.lm_params_to_3d)
    "embed/tok_embed/embedding",
    "embed/pos_embed/embedding",
    "blocks/ln1/scale",
    "blocks/qkv/kernel",
    "blocks/q/kernel",
    "blocks/kv/kernel",
    "blocks/proj/kernel",
    "blocks/mlp_in/kernel",
    "blocks/mlp_out/kernel",
    "blocks/moe/router/kernel",
    "blocks/moe/w_in",
    "blocks/moe/w_out",
    "out/ln_f/scale",
    "out/head/kernel",
)


def path_names(path):
    """Flax/jax tree path entries -> their string names (DictKey.key,
    GetAttrKey.name, SequenceKey.idx)."""
    out = []
    for p in path:
        for attr in ("key", "name", "idx"):
            if hasattr(p, attr):
                out.append(str(getattr(p, attr)))
                break
        else:
            out.append(str(p))
    return out


def path_name(path) -> str:
    """The ``/``-joined leaf name rule regexes match against."""
    return "/".join(path_names(path))


def _leaf_shape(leaf):
    shape = getattr(leaf, "shape", None)
    return tuple(shape) if shape is not None else ()


def spec_for(rules: RuleTable, name: str, leaf=None) -> P:
    """First-match-wins lookup of one leaf's PartitionSpec.  Scalar /
    size-1 leaves replicate unconditionally; a leaf no rule matches
    raises (a silent default would be exactly the silent-replication
    bug rule tables exist to prevent — close tables with ``(".*",
    P())`` when replication IS the intent)."""
    if leaf is not None:
        shape = _leaf_shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) <= 1:
            return P()
    for pattern, spec in rules:
        if re.search(pattern, name):
            return spec
    raise ValueError(
        f"no partition rule matched leaf {name!r} — add a rule (or a "
        f'catch-all (".*", P()) row) to the table')


def match_partition_rules(rules: RuleTable, tree):
    """Spec tree for `tree`: each leaf gets the first rule whose regex
    matches its ``/``-joined path name (scalars replicate)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for(rules, path_name(path), leaf), tree)


def make_shard_and_gather_fns(partition_specs, mesh: Mesh):
    """(shard_fns, gather_fns) trees matching `partition_specs`:
    shard_fn places a host leaf onto the mesh under its spec;
    gather_fn pulls a (possibly sharded) leaf back to host numpy —
    the save/restore side of the same rule table."""
    is_spec = lambda x: isinstance(x, P)

    def mk_shard(spec):
        sharding = NamedSharding(mesh, spec)
        return lambda x: jax.device_put(x, sharding)

    def mk_gather(_spec):
        return lambda x: np.asarray(jax.device_get(x))

    shard_fns = jax.tree.map(mk_shard, partition_specs, is_leaf=is_spec)
    gather_fns = jax.tree.map(mk_gather, partition_specs, is_leaf=is_spec)
    return shard_fns, gather_fns


def validate_rules(rules: RuleTable, axes: Iterable[str]) -> None:
    """Every axis name any rule's spec mentions must be a declared mesh
    axis — the runtime twin of graftlint G305 (a typo'd axis name makes
    XLA silently replicate the leaf; nothing errors, MFU just dies)."""
    axes = set(axes)
    for pattern, spec in rules:
        for entry in tuple(spec):
            names = entry if isinstance(entry, (tuple, list)) else (entry,)
            for n in names:
                if n is not None and n not in axes:
                    raise ValueError(
                        f"rule {pattern!r} uses axis {n!r} not in the "
                        f"mesh axes {sorted(axes)} — a typo here would "
                        f"silently replicate the leaf")


def validate_coverage(rules: RuleTable,
                      paths: Iterable[str] = PARAM_PATH_MANIFEST) -> None:
    """Every manifest path must match some rule — the runtime twin of
    graftlint G503 for tables built dynamically (where the static pass
    sees no literal).  Raises on the first uncovered path, naming it,
    instead of letting `spec_for` raise mid-shard on a real tree."""
    for name in paths:
        spec_for(rules, name)  # raises ValueError on no match


# ------------------------------------------------------------ rule tables

def lm_tensor_rules(axis: str = "model") -> RuleTable:
    """TransformerLM block/head kernels over the tensor axis: qkv/mlp_in/
    head shard output features, proj/mlp_out shard input features (the
    megatron pairing — one all-reduce per block, none inside the MLP).
    'qkv' is the fused MHA projection; GQA splits it into 'q' + 'kv'."""
    return (
        (r"(^|/)(qkv|q|kv|mlp_in|head)/kernel$", P(None, axis)),
        (r"(^|/)(proj|mlp_out)/kernel$", P(axis, None)),
        (r".*", P()),
    )


def moe_expert_rules(axis: str = "model") -> RuleTable:
    """Shard the EXPERT dim of switch-MoE w_in/w_out (expert parallelism);
    everything else replicates."""
    return (
        (r"(^|/)moe/(w_in|w_out)$", P(axis, None, None)),
        (r".*", P()),
    )


def head_only_rules(axis: str = "model") -> RuleTable:
    """Classifier-head-only sharding (the CNN fine-tune shape: one big
    dense head, convs replicated)."""
    return (
        (r"(^|/)head/kernel$", P(None, axis)),
        (r".*", P()),
    )


def lm_3d_rules(tensor_axis: str = "model",
                pipe_axis: str = "pipe") -> RuleTable:
    """Rules for the STACKED 3D-trainer layout (``lm_params_to_3d``):
    block params carry leading [P_stages, K_blocks] dims sharded over the
    pipe axis, with the megatron tensor pairing on the trailing kernel
    dims; embed/ln replicate; head shards its vocab dim."""
    return (
        (r"^blocks/.*(qkv|q|kv|mlp_in)/kernel$",
         P(pipe_axis, None, None, tensor_axis)),
        (r"^blocks/.*(proj|mlp_out)/kernel$",
         P(pipe_axis, None, tensor_axis, None)),
        (r"^blocks/.*moe/(w_in|w_out)$",
         P(pipe_axis, None, tensor_axis, None, None)),
        # everything else under blocks/ (ln scale/bias, dense biases,
        # router) shards only its stage dim
        (r"^blocks/", P(pipe_axis)),
        (r"^out/head/kernel$", P(None, tensor_axis)),
        (r".*", P()),
    )


# ------------------------------------------ legacy callable adapters
# The pre-rule-library surface: (path, arr) -> spec callables.  Each is
# now a one-line lookup into its table — the name matching lives in ONE
# place (spec_for) instead of three hand-rolled copies.

def lm_tensor_parallel_rules(path, arr, axis: str = "model") -> P:
    return spec_for(lm_tensor_rules(axis), path_name(path), arr)


def moe_expert_parallel_rules(path, arr, axis: str = "model") -> P:
    return spec_for(moe_expert_rules(axis), path_name(path), arr)


def head_rules(path, arr, axis: str = "model") -> P:
    return spec_for(head_only_rules(axis), path_name(path), arr)
