"""Device mesh management: the framework's parallelism substrate.

The reference's parallelism is Spark partitions + sockets (SURVEY.md §2.10);
here every distributed computation runs SPMD over a `jax.sharding.Mesh` with
named axes:

    data    — batch/data parallel (the reference's mapPartitions analog)
    model   — tensor parallel (reserved; reference has none)
    seq     — sequence/context parallel for long inputs (ring attention)

XLA inserts the collectives (psum/all_gather/reduce_scatter) from sharding
annotations; they ride ICI within a slice and DCN across slices.
"""
from __future__ import annotations

import contextlib
import inspect
import math
from typing import Dict, Optional, Sequence, Tuple

import jax

try:  # jax >= 0.6 re-exports shard_map at the top level
    from jax import shard_map as _shard_map
except ImportError:  # the 0.4.x line keeps it in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

_SM_HAS_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, **kw):
    """Version-compat `shard_map`: ONE import site for the whole package
    (jax moved it out of experimental in 0.6 and renamed `check_rep` to
    `check_vma` with the varying-manual-axes type system in 0.7 — every
    caller goes through here so no module breaks on either line)."""
    if "check_vma" in kw and not _SM_HAS_VMA:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and _SM_HAS_VMA:
        kw["check_vma"] = kw.pop("check_rep")
    return _shard_map(f, **kw)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "MESH_AXIS_NAMES",
    "make_mesh",
    "MeshPlan",
    "default_mesh",
    "MeshContext",
    "batch_sharding",
    "replicated_sharding",
    "addressable_shard_layout",
    "host_device_groups",
    "shard_batch",
    "pad_to_multiple",
]

# Every axis name a mesh in this codebase may declare.  graftlint G501
# (né G305) checks any axis literal inside a PartitionSpec — and any
# collective's axis_name — against this tuple (a typo'd axis name does
# not error — XLA silently replicates the leaf), and
# sharding_rules.validate_rules does the same at runtime.  Keep it a
# plain tuple literal: the lint parses it via AST without importing jax.
MESH_AXIS_NAMES = ("data", "model", "seq", "pipe")

_CURRENT: Dict[str, Optional[Mesh]] = {"mesh": None}


def make_mesh(
    data: int = -1,
    model: int = 1,
    seq: int = 1,
    devices: Optional[Sequence] = None,
    dcn_data: int = 1,
) -> Mesh:
    """Build a (data, model, seq) mesh.  `data=-1` absorbs remaining devices.

    `dcn_data` > 1 declares a multi-slice layout: the data axis's leading
    `dcn_data` blocks each live on one slice, so only data-parallel
    collectives cross DCN while model/seq collectives stay on ICI (the
    scaling-book slice layout; placement comes from
    utils.cluster.device_topology rather than raw device order).  When the
    runtime reports fewer slices than requested (the virtual CPU test
    mesh), devices are grouped into `dcn_data` contiguous virtual slices so
    the layout still compiles and is exercised by tests/dryruns.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % (model * seq) != 0:
            raise ValueError(f"{n} devices not divisible by model*seq={model * seq}")
        data = n // (model * seq)
    if data * model * seq != n:
        raise ValueError(f"mesh {data}x{model}x{seq} != {n} devices")
    if dcn_data > 1:
        from ..utils.cluster import device_topology

        if data % dcn_data != 0:
            raise ValueError(f"data={data} not divisible by dcn_data={dcn_data}")
        topo = device_topology(devices)
        if topo.num_slices == dcn_data:
            groups = topo.slice_groups()
        elif topo.num_slices <= 1:
            # single-slice / virtual runtimes (the CPU test mesh): contiguous
            # equal groups emulate slices so the layout still compiles
            per = n // dcn_data
            groups = [list(range(g * per, (g + 1) * per))
                      for g in range(dcn_data)]
        else:
            # a real multi-slice job with a mismatched request must not be
            # silently laid out across slice boundaries
            raise ValueError(
                f"dcn_data={dcn_data} does not match the runtime's "
                f"{topo.num_slices} slices")
        if len({len(g) for g in groups}) != 1:
            raise ValueError("unequal slice sizes cannot form a mesh")
        # slice-major ordering puts the DCN boundary on the leading blocks
        # of the data axis
        ordered = [devices[i] for g in groups for i in g]
        arr = np.asarray(ordered).reshape(data, model, seq)
    else:
        arr = np.asarray(devices).reshape(data, model, seq)
    return Mesh(arr, axis_names=("data", "model", "seq"))


class MeshPlan:
    """One (data, model, pipe) layout for the 3D-mesh GSPMD trainer:
    D-way data parallelism x T-way megatron tensor parallelism x P-way
    GPipe pipeline parallelism on a SINGLE mesh, so XLA composes all
    three collective families in one program (the make_lm_train_step_3d
    substrate; docs/performance.md "The 3D mesh").

    ``data=-1`` absorbs the remaining devices.  The axis names are the
    plan's contract with every partition-rule table — `validate_specs`
    is the runtime check graftlint G501 (né G305) performs statically."""

    AXES = ("data", "model", "pipe")

    def __init__(self, data: int = -1, model: int = 1, pipe: int = 1,
                 devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        n = len(devices)
        if model < 1 or pipe < 1:
            raise ValueError(f"model={model} and pipe={pipe} must be >= 1")
        if data == -1:
            if n % (model * pipe) != 0:
                raise ValueError(
                    f"{n} devices not divisible by model*pipe="
                    f"{model * pipe}")
            data = n // (model * pipe)
        if data * model * pipe != n:
            raise ValueError(
                f"mesh plan {data}x{model}x{pipe} != {n} devices")
        self.data, self.model, self.pipe = int(data), int(model), int(pipe)
        arr = np.asarray(devices).reshape(self.data, self.model, self.pipe)
        self.mesh = Mesh(arr, axis_names=self.AXES)

    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.mesh.shape)

    def validate_specs(self, rules) -> None:
        """Raise if any rule's spec names an axis this plan's mesh does
        not declare (the silent-full-replication typo G501 catches in
        source)."""
        from .sharding_rules import validate_rules

        validate_rules(rules, self.AXES)

    def __repr__(self) -> str:
        return (f"MeshPlan(data={self.data}, model={self.model}, "
                f"pipe={self.pipe})")


def default_mesh() -> Mesh:
    """The ambient mesh: explicitly-entered MeshContext, else all devices on
    the data axis."""
    if _CURRENT["mesh"] is not None:
        return _CURRENT["mesh"]
    return make_mesh()


@contextlib.contextmanager
def MeshContext(mesh: Mesh):
    prev = _CURRENT["mesh"]
    _CURRENT["mesh"] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _CURRENT["mesh"] = prev


def batch_sharding(mesh: Mesh, ndim: int = 1, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch axis over 'data'; everything else replicated."""
    spec = [None] * ndim
    spec[batch_axis] = "data"
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def addressable_shard_layout(sharding, shape):
    """[(device, index)] for every addressable shard of `shape` under
    `sharding`, in stable device-id order — or None when the shape does
    not divide evenly (callers fall back to one coalesced transfer).

    This is the substrate of the sharded direct-to-chip path
    (io/shard_put.py): each (device, index) pair becomes ONE
    `jax.device_put(arr[index], device)` riding its own transfer stream,
    and the shards reassemble zero-copy with
    `jax.make_array_from_single_device_arrays`."""
    try:
        imap = sharding.addressable_devices_indices_map(tuple(shape))
    except (ValueError, TypeError):
        return None
    if not imap or any(idx is None for idx in imap.values()):
        return None
    return sorted(imap.items(), key=lambda di: di[0].id)


def host_device_groups(devices, num_hosts: int):
    """Partition `devices` into `num_hosts` equal contiguous groups — the
    simulated-host layout elastic tests and soaks use on the forced
    virtual CPU mesh (tools/dist_soak.py leg A treats 8 devices as
    4 hosts x 2 chips).  A real pod never calls this: per-process
    addressability already partitions the device set, and
    `addressable_shard_layout` above is per-host by construction."""
    devices = list(devices)
    n = len(devices)
    if num_hosts < 1 or n % num_hosts != 0:
        raise ValueError(
            f"{n} devices do not split into {num_hosts} equal hosts")
    per = n // num_hosts
    return [devices[i * per:(i + 1) * per] for i in range(num_hosts)]


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0) -> Tuple[np.ndarray, int]:
    """Pad `axis` up to a multiple (static shapes for XLA; padded rows are
    dropped after unbatching).  Returns (padded, original_len)."""
    n = arr.shape[axis]
    target = math.ceil(max(n, 1) / multiple) * multiple
    if target == n:
        return arr, n
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(arr, pad_width, mode="edge"), n


def shard_batch(arr: np.ndarray, mesh: Optional[Mesh] = None) -> Tuple[jax.Array, int]:
    """Pad the leading axis to the data-parallel degree and device_put with a
    batch sharding — the device-feed path replacing the reference's
    mapPartitions dispatch (CNTKModel.scala:526-531).
    """
    mesh = mesh or default_mesh()
    dp = mesh.shape["data"]
    padded, n = pad_to_multiple(np.asarray(arr), dp, axis=0)
    out = jax.device_put(padded, batch_sharding(mesh, padded.ndim))
    return out, n
