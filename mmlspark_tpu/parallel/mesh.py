"""Device mesh management: the framework's parallelism substrate.

The reference's parallelism is Spark partitions + sockets (SURVEY.md §2.10);
here every distributed computation runs SPMD over a `jax.sharding.Mesh` with
named axes:

    data    — batch/data parallel (the reference's mapPartitions analog)
    model   — tensor parallel (reserved; reference has none)
    seq     — sequence/context parallel for long inputs (ring attention)

XLA inserts the collectives (psum/all_gather/reduce_scatter) from sharding
annotations; they ride ICI within a slice and DCN across slices.
"""
from __future__ import annotations

import contextlib
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "make_mesh",
    "default_mesh",
    "MeshContext",
    "batch_sharding",
    "replicated_sharding",
    "shard_batch",
    "pad_to_multiple",
]

_CURRENT: Dict[str, Optional[Mesh]] = {"mesh": None}


def make_mesh(
    data: int = -1,
    model: int = 1,
    seq: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a (data, model, seq) mesh.  `data=-1` absorbs remaining devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % (model * seq) != 0:
            raise ValueError(f"{n} devices not divisible by model*seq={model * seq}")
        data = n // (model * seq)
    if data * model * seq != n:
        raise ValueError(f"mesh {data}x{model}x{seq} != {n} devices")
    arr = np.asarray(devices).reshape(data, model, seq)
    return Mesh(arr, axis_names=("data", "model", "seq"))


def default_mesh() -> Mesh:
    """The ambient mesh: explicitly-entered MeshContext, else all devices on
    the data axis."""
    if _CURRENT["mesh"] is not None:
        return _CURRENT["mesh"]
    return make_mesh()


@contextlib.contextmanager
def MeshContext(mesh: Mesh):
    prev = _CURRENT["mesh"]
    _CURRENT["mesh"] = mesh
    try:
        with mesh:
            yield mesh
    finally:
        _CURRENT["mesh"] = prev


def batch_sharding(mesh: Mesh, ndim: int = 1, batch_axis: int = 0) -> NamedSharding:
    """Shard the batch axis over 'data'; everything else replicated."""
    spec = [None] * ndim
    spec[batch_axis] = "data"
    return NamedSharding(mesh, P(*spec))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0) -> Tuple[np.ndarray, int]:
    """Pad `axis` up to a multiple (static shapes for XLA; padded rows are
    dropped after unbatching).  Returns (padded, original_len)."""
    n = arr.shape[axis]
    target = math.ceil(max(n, 1) / multiple) * multiple
    if target == n:
        return arr, n
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, target - n)
    return np.pad(arr, pad_width, mode="edge"), n


def shard_batch(arr: np.ndarray, mesh: Optional[Mesh] = None) -> Tuple[jax.Array, int]:
    """Pad the leading axis to the data-parallel degree and device_put with a
    batch sharding — the device-feed path replacing the reference's
    mapPartitions dispatch (CNTKModel.scala:526-531).
    """
    mesh = mesh or default_mesh()
    dp = mesh.shape["data"]
    padded, n = pad_to_multiple(np.asarray(arr), dp, axis=0)
    out = jax.device_put(padded, batch_sharding(mesh, padded.ndim))
    return out, n
