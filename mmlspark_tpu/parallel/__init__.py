from .mesh import (
    MESH_AXIS_NAMES,
    MeshContext,
    MeshPlan,
    batch_sharding,
    default_mesh,
    make_mesh,
    replicated_sharding,
    shard_batch,
)
from .distributed import initialize_distributed, barrier
from .sharding_rules import (
    PARAM_PATH_MANIFEST,
    match_partition_rules,
    validate_coverage,
    validate_rules,
)
