from .mesh import (
    MESH_AXIS_NAMES,
    MeshContext,
    MeshPlan,
    batch_sharding,
    default_mesh,
    make_mesh,
    replicated_sharding,
    shard_batch,
)
from .distributed import initialize_distributed, barrier
