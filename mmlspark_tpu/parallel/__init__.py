from .mesh import (
    MESH_AXIS_NAMES,
    MeshContext,
    MeshPlan,
    batch_sharding,
    default_mesh,
    host_device_groups,
    make_mesh,
    replicated_sharding,
    shard_batch,
)
from .distributed import (
    DIST_FAULT_POINTS,
    CollectiveTimeout,
    ElasticContext,
    HeartbeatMonitor,
    HostInfo,
    HostTelemetryServer,
    MembershipStore,
    MembershipView,
    RendezvousError,
    StaleMembershipError,
    barrier,
    initialize_distributed,
    is_coordinator,
    local_host_info,
    run_with_deadline,
)
from .sharding_rules import (
    PARAM_PATH_MANIFEST,
    match_partition_rules,
    validate_coverage,
    validate_rules,
)
