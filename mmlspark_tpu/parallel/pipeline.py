"""Pipeline parallelism: a GPipe schedule as shard_map + ppermute.

The last of the mesh parallelisms (dp/tp/sp/ep live elsewhere): P pipeline
stages hold their own slice of a stacked parameter pytree (leading dim P,
sharded over a mesh axis), microbatches stream through the stage chain
with activations hopping stage-to-stage over `ppermute` — the classic
bubble schedule (M + P - 1 steps for M microbatches; bubble fraction
(P-1)/(M+P-1)).

TPU-first shape: ONE jitted program — the schedule is a `lax.scan`, the
inter-stage hop is a collective XLA lowers onto ICI, and the whole thing
is differentiable (ppermute transposes to the reverse hop), so training
backprops through the pipe with no custom VJP.

The reference has no model parallelism of any kind (SURVEY §2.10 last
row); this is beyond-reference infrastructure shaped by the same
mesh/collective design as the rest of `parallel/`.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import shard_map

__all__ = ["pipeline_apply", "gpipe_spmd_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> one pytree with leading dim P (stage axis) —
    the layout `pipeline_apply` shards over the mesh axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(stage_fn: Callable, stacked_params, x: jnp.ndarray,
                   mesh: Mesh, axis: str = "model") -> jnp.ndarray:
    """Run `x [M, mb, ...]` microbatches through P chained stages.

    stage_fn(params_i, x) -> same-shaped activation; `stacked_params` has
    leading dim P == mesh.shape[axis], sharded so stage i's weights live
    on pipe rank i.  Returns [M, mb, ...] outputs (replicated), equal to
    applying the P stages sequentially to each microbatch.
    """
    n_stages = mesh.shape[axis]
    leading = {a.shape[0] for a in jax.tree.leaves(stacked_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stacked_params leading dim(s) {sorted(leading)} must equal "
            f"mesh axis {axis!r} size {n_stages} — one stage per pipe "
            "rank (a clean multiple would silently run every k-th stage)")
    m = x.shape[0]
    steps = m + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_stage(params_local, xs):
        params_local = jax.tree.map(lambda a: a[0], params_local)
        rank = jax.lax.axis_index(axis)
        # the carry becomes device-varying after the first ppermute; the
        # zero init must carry the same varying-axes type (jax >= 0.7
        # tracks varying manual axes — 0.4.x shard_map has no such type,
        # so there the plain zeros carry is already correct)
        buf = jnp.zeros_like(xs[0])
        pcast = getattr(jax.lax, "pcast", None)
        if pcast is not None:
            buf = pcast(buf, (axis,), to="varying")

        def body(buf, t):
            # stage 0 ingests microbatch t (while any remain); downstream
            # stages consume what the previous stage ppermuted to them
            inp = jnp.where(rank == 0,
                            xs[jnp.clip(t, 0, m - 1)], buf)
            out = stage_fn(params_local, inp)
            nxt = jax.lax.ppermute(out, axis, perm)
            # the LAST stage's output at step t is microbatch t-(P-1)
            return nxt, out

        _, outs = jax.lax.scan(body, buf, jnp.arange(steps))
        # outs [steps, mb, ...]: keep the last stage's valid window and
        # replicate it to every rank (other ranks contribute zeros)
        window = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, m, axis=0)
        mine = jnp.where(rank == n_stages - 1, window, 0)
        return jax.lax.psum(mine, axis)

    spec_p = jax.tree.map(lambda _: P(axis), stacked_params)
    return shard_map(
        per_stage, mesh=mesh,
        in_specs=(spec_p, P()), out_specs=P(),
    )(stacked_params, x)


def gpipe_spmd_apply(stage_fn: Callable, stacked_params, x: jnp.ndarray,
                     mesh: Mesh = None, axis: str = "pipe",
                     batch_axis: str = "data") -> jnp.ndarray:
    """The SAME M + P - 1 GPipe schedule as :func:`pipeline_apply`,
    lowered through GSPMD sharding annotations instead of shard_map —
    which is what lets it COMPOSE with data-parallel batch sharding and
    megatron tensor rules on one 3D mesh (shard_map bodies see local
    arrays; tensor-parallel collectives inside them would have to be
    hand-written, and jax 0.4.x cannot mix auto axes in).

    ``x [M, mb, ...]`` microbatches; ``stacked_params`` leaves carry a
    leading stage dim P (any further leading dims — e.g. the
    [P, K_blocks] layout of ``lm_params_to_3d`` — are stage-private).
    The schedule is a `lax.scan` whose donated carry is the [P, mb, ...]
    activation buffer: each tick runs every stage in parallel
    (``jax.vmap`` over the stage dim, which XLA partitions over the pipe
    axis), then the buffer rolls one stage forward — `jnp.roll` on a
    pipe-sharded dim lowers to the same collective-permute hop
    pipeline_apply issues by hand.  Differentiable end to end; returns
    [M, mb, ...] equal to applying the stages sequentially.
    """
    leading = {a.shape[0] for a in jax.tree.leaves(stacked_params)}
    if len(leading) != 1:
        raise ValueError(
            f"stacked_params leading dims differ: {sorted(leading)}")
    (p,) = leading
    if mesh is not None and axis in mesh.shape and mesh.shape[axis] != p:
        raise ValueError(
            f"stacked_params leading dim {p} != mesh axis {axis!r} size "
            f"{mesh.shape[axis]} — one stage per pipe rank")
    m = x.shape[0]
    steps = m + p - 1
    vstage = jax.vmap(stage_fn)

    def pin(buf):
        # keep the buffer stage-dim on the pipe axis and the microbatch
        # dim data-sharded at every tick, so the roll stays a pure
        # neighbor hop instead of a resharding
        if mesh is None:
            return buf
        return jax.lax.with_sharding_constraint(
            buf, NamedSharding(mesh, P(axis, batch_axis)))

    def body(buf, t):
        # stage 0 ingests microbatch t (clamped: drain ticks feed a dead
        # row that ys slicing discards); stages 1..P-1 consume what the
        # previous tick rolled to them
        inp = jax.lax.dynamic_index_in_dim(x, jnp.clip(t, 0, m - 1), 0,
                                           keepdims=False)
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, inp.astype(buf.dtype), 0, 0)
        out = pin(vstage(stacked_params, buf))
        # the LAST stage's output at tick t is microbatch t - (P-1)
        y = out[p - 1]
        return pin(jnp.roll(out, 1, axis=0)), y

    buf0 = pin(jnp.zeros((p,) + x.shape[1:], x.dtype))
    _, ys = jax.lax.scan(body, buf0, jnp.arange(steps))
    return ys[p - 1:]
